"""Coordinator HTTP protocol tests (reference style: TestServer +
client StatementClientV1 round-trips)."""

from decimal import Decimal

import pytest

from trino_tpu.client import Client, QueryFailed
from trino_tpu.server import CoordinatorServer


@pytest.fixture(scope="module")
def server():
    s = CoordinatorServer(port=0)  # ephemeral port
    s.start()
    yield s
    s.shutdown()


@pytest.fixture(scope="module")
def client(server):
    return Client(f"http://127.0.0.1:{server.port}")


def test_protocol_roundtrip(client):
    names, rows = client.execute("select 1 as a, 'x' as b, null as c")
    assert names == ["a", "b", "c"]
    assert rows == [(1, "x", None)]


def test_typed_values(client):
    names, rows = client.execute(
        "select n_name, n_regionkey from tpch.tiny.nation order by n_name limit 2"
    )
    assert rows[0][0] == "ALGERIA"
    names, rows = client.execute("select sum(r_regionkey) * 1.5 from tpch.tiny.region")
    assert rows[0][0] == Decimal("15.0")


def test_paging(client):
    # customer tiny has 1500 rows; forces multiple result pages (4096 cap,
    # use a cross join to exceed it)
    names, rows = client.execute(
        "select n1.n_nationkey from tpch.tiny.nation n1, tpch.tiny.nation n2, "
        "tpch.tiny.nation n3"
    )
    assert len(rows) == 25 * 25 * 25


def test_error_surface(client):
    with pytest.raises(QueryFailed) as ei:
        client.execute("select no_such_column from tpch.tiny.region")
    assert "no_such_column" in str(ei.value)


def test_cli_format():
    from trino_tpu.cli import format_table

    text = format_table(["a", "bb"], [(1, "x"), (None, "longer")])
    lines = text.splitlines()
    assert lines[0].startswith("a ") and "bb" in lines[0]
    assert "NULL" in text and "(2 rows)" in text


def test_metrics_endpoint(server, client):
    import urllib.request

    client.execute("select count(*) from tpch.tiny.nation")
    req = urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/v1/metrics", timeout=10
    )
    assert req.status == 200
    assert req.headers["Content-Type"].startswith("text/plain")
    text = req.read().decode()
    # valid Prometheus exposition exposing trace-cache + exchange counters
    assert "# TYPE trino_tpu_queries_total counter" in text
    assert "trino_tpu_trace_cache_hits_total" in text
    assert 'trino_tpu_mesh_events_total{counter="exchange_elided"}' in text
    assert "trino_tpu_query_wall_seconds_count" in text


def test_query_trace_endpoint(server):
    import json
    import urllib.request
    from urllib.error import HTTPError

    q = server.submit("select count(*) from tpch.tiny.region")
    assert q.done.wait(timeout=30)
    req = urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/v1/query/{q.id}/trace", timeout=10
    )
    doc = json.loads(req.read().decode())
    names = [e["name"] for e in doc["traceEvents"]]
    assert "query" in names and "execute" in names
    with pytest.raises(HTTPError):
        urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/v1/query/nope/trace", timeout=10
        )


def test_ui_stats_carry_trace_cache(server):
    import json
    import urllib.request

    req = urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/ui/api/stats", timeout=10
    )
    doc = json.loads(req.read().decode())
    assert doc["metricsUri"] == "/v1/metrics"
    assert "retraces" in doc.get("traceCache", {})
