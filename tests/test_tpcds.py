"""TPC-DS connector + query tests (reference style: TestTpcdsMetadata +
tpcds query smoke suites)."""

import numpy as np
import pytest


from trino_tpu.connectors.tpcds import TpcdsConnector
from trino_tpu.connectors.tpcds.queries import QUERIES
from trino_tpu.connectors.tpcds.schema import TABLES
from trino_tpu.runtime.runner import LocalQueryRunner
from trino_tpu.testing import connector_table_to_pandas


@pytest.fixture(scope="module", autouse=True)
def _fresh_caches():
    """The TPC-DS module compiles hundreds of fragment kernels; entering it
    with the whole suite's accumulated executables has hit allocator-level
    XLA crashes late in the run.  Start from a clean compile cache and an
    empty buffer pool (everything recompiles on demand).

    (The periodic purge below also keeps the allocator fresh enough that the
    persistent-cache writer — which segfaulted when hundreds of executables
    had accumulated — stays safe, and purged kernels RELOAD from disk
    instead of recompiling.)"""
    import jax

    from trino_tpu.runtime.buffer_pool import POOL

    jax.clear_caches()
    POOL.clear()
    yield
    jax.clear_caches()
    POOL.clear()


_TEST_TICK = {"n": 0}


@pytest.fixture(autouse=True)
def _periodic_executable_purge():
    """The allocator corruption above is reached WITHIN this module too
    (XLA:CPU segfaults compiling around the ~45th query with hundreds of
    live executables).  Purge every few tests; queries recompile their own
    kernels, correctness is unaffected."""
    yield
    _TEST_TICK["n"] += 1
    if _TEST_TICK["n"] % 10 == 0:
        import jax

        from trino_tpu.runtime.buffer_pool import POOL

        jax.clear_caches()
        POOL.clear()


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner(catalog="tpcds", schema="tiny", target_splits=2)


def test_all_tables_scan(runner):
    for table in sorted(TABLES):
        res = runner.execute(f"select count(*) from {table}")
        assert res.rows[0][0] > 0, table


def test_schema_columns(runner):
    cols = dict(runner.execute("describe item").rows)
    assert cols["i_item_sk"] == "bigint"
    assert cols["i_current_price"] == "decimal(7,2)"
    assert len(cols) == 22


def test_calendar_dimension(runner):
    rows = runner.execute(
        "select min(d_year), max(d_year), count(*) from date_dim"
    ).rows
    assert rows == [(1900, 2099, 73049)]
    # d_date_sk is a julian day number aligned with d_date
    rows = runner.execute(
        "select count(*) from date_dim where d_year = 2000 and d_moy = 2"
    ).rows
    assert rows == [(29,)]  # Feb 2000 (leap)


def test_fact_dimension_fk(runner):
    joined = runner.execute(
        "select count(*), min(d_year), max(d_year) "
        "from store_sales, date_dim where ss_sold_date_sk = d_date_sk"
    ).rows
    n, lo, hi = joined[0]
    assert n > 25_000 and lo >= 1998 and hi <= 2003


def test_returns_link_to_sales(runner):
    # every store_returns row copies its parent sale's (item, ticket) keys,
    # so the sales<->returns join matches every return row at least once
    total = runner.execute("select count(*) from store_returns").rows[0][0]
    joined = runner.execute(
        "select count(*) from store_sales, store_returns "
        "where ss_item_sk = sr_item_sk and ss_ticket_number = sr_ticket_number"
    ).rows[0][0]
    # ~4% of fact FKs are NULL (spec-shaped), so a small fraction of return
    # rows carry a NULL item key and cannot join
    assert total > 0 and joined >= 0.9 * total


def test_demographics_crossproduct(runner):
    rows = runner.execute("select count(*) from customer_demographics").rows
    assert rows == [(1_920_800,)]
    g = runner.execute(
        "select count(distinct cd_gender) from customer_demographics"
    ).rows
    assert g == [(2,)]


def _norm(v):
    import datetime
    import decimal
    import math

    if isinstance(v, decimal.Decimal):
        return float(v)
    if isinstance(v, (datetime.date, datetime.datetime)):
        return v.isoformat()
    if isinstance(v, float) and math.isnan(v):
        return None
    return v


def _approx(a, b, atol=0.02):
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, float) or isinstance(b, float):
        try:
            fa, fb = float(a), float(b)
        except (TypeError, ValueError):
            return False
        return abs(fa - fb) <= atol + 1e-6 * max(abs(fa), abs(fb))
    return a == b


def assert_same_rows(actual, expected):
    actual = [tuple(_norm(v) for v in r) for r in actual]
    expected = [tuple(_norm(v) for v in r) for r in expected]
    assert len(actual) == len(expected), (
        f"row count {len(actual)} != {len(expected)}\n"
        f"actual[:3]={actual[:3]}\nexpected[:3]={expected[:3]}"
    )
    key = lambda r: tuple("\0" if v is None else str(v) for v in r)
    for i, (ra, re_) in enumerate(
        zip(sorted(actual, key=key), sorted(expected, key=key))
    ):
        assert len(ra) == len(re_), f"row {i} width"
        for j, (va, ve) in enumerate(zip(ra, re_)):
            assert _approx(va, ve), (
                f"row {i} col {j}: {va!r} != {ve!r}\n{ra}\n{re_}"
            )


@pytest.mark.parametrize("qid", sorted(QUERIES))
def test_tpcds_query_vs_oracle(runner, qid):
    """Every workload query executes end-to-end AND matches the independent
    sqlite3 oracle (reference style: H2QueryRunner assertQuery).

    ROLLUP queries (sqlite has no grouping sets) check through a chain:
    engine(rollup) == engine(union-expansion) == sqlite(union-expansion) —
    see tests/tpcds_rollup_equiv.py."""
    from tests.tpcds_oracle import run_sqlite
    from tests.tpcds_rollup_equiv import EQUIV

    engine = runner.execute(QUERIES[qid])
    if qid in EQUIV:
        expanded = runner.execute(EQUIV[qid])
        assert_same_rows(engine.rows, expanded.rows)
        oracle = run_sqlite(EQUIV[qid])
        assert_same_rows(expanded.rows, oracle)
    else:
        oracle = run_sqlite(QUERIES[qid])
        assert_same_rows(engine.rows, oracle)


def test_q96_matches_pandas(runner):
    conn = runner.catalogs.get("tpcds")
    t = lambda name: connector_table_to_pandas(conn, "tiny", name)
    ss, hd, td, s = t("store_sales"), t("household_demographics"), t("time_dim"), t("store")
    j = (
        ss.merge(td, left_on="ss_sold_time_sk", right_on="t_time_sk")
        .merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        .merge(s, left_on="ss_store_sk", right_on="s_store_sk")
    )
    j = j[(j.t_hour == 20) & (j.t_minute >= 30) & (j.hd_dep_count == 7) & (j.s_store_name == "ese")]
    expected = len(j)
    got = runner.execute(QUERIES[96]).rows[0][0]
    assert got == expected
