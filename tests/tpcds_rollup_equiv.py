"""UNION ALL expansions of the ROLLUP TPC-DS queries.

sqlite has no GROUPING SETS, so these rollup queries are oracle-checked
through a chain: engine(rollup) == engine(union-expansion) and
engine(union-expansion) == sqlite(union-expansion).  The expansion is the
textbook rollup semantics (one plain GROUP BY per level, masked keys NULL,
grouping() replaced by per-level literals), so the first equality validates
the GroupId lowering and the second validates everything else.
"""

# Q5/Q77/Q80 share the rollup tail `group by rollup(channel, id)` over a
# derived union `x`; the expansion wraps the SAME x three ways.
def _channel_id_rollup(body: str) -> str:
    return f"""
select channel, id, sum(sales) as sales, sum(returns_) as returns_,
       sum(profit) as profit
from ({body}) x
group by channel, id
union all
select channel, null as id, sum(sales), sum(returns_), sum(profit)
from ({body}) x
group by channel
union all
select null as channel, null as id, sum(sales), sum(returns_), sum(profit)
from ({body}) x
order by channel nulls last, id nulls last
limit 100
"""


_Q5_BODY = """
    select 'store channel' as channel, 'store' || s_store_id as id,
           sales, returns_, profit - profit_loss as profit
    from (select s_store_id,
                 sum(sales_price) as sales, sum(profit) as profit,
                 sum(return_amt) as returns_, sum(net_loss) as profit_loss
          from (select ss_store_sk as store_sk, ss_sold_date_sk as date_sk,
                       ss_ext_sales_price as sales_price,
                       ss_net_profit as profit,
                       cast(0 as double) as return_amt,
                       cast(0 as double) as net_loss
                from store_sales
                union all
                select sr_store_sk, sr_returned_date_sk,
                       cast(0 as double), cast(0 as double),
                       sr_return_amt, sr_net_loss
                from store_returns) salesreturns, date_dim, store
          where date_sk = d_date_sk
            and d_date between cast('2000-08-23' as date)
                           and cast('2000-08-23' as date) + interval '14' day
            and store_sk = s_store_sk
          group by s_store_id) ssr
    union all
    select 'catalog channel' as channel,
           'catalog_page' || cp_catalog_page_id as id,
           sales, returns_, profit - profit_loss as profit
    from (select cp_catalog_page_id,
                 sum(sales_price) as sales, sum(profit) as profit,
                 sum(return_amt) as returns_, sum(net_loss) as profit_loss
          from (select cs_catalog_page_sk as page_sk,
                       cs_sold_date_sk as date_sk,
                       cs_ext_sales_price as sales_price,
                       cs_net_profit as profit,
                       cast(0 as double) as return_amt,
                       cast(0 as double) as net_loss
                from catalog_sales
                union all
                select cr_catalog_page_sk, cr_returned_date_sk,
                       cast(0 as double), cast(0 as double),
                       cr_return_amount, cr_net_loss
                from catalog_returns) salesreturns, date_dim, catalog_page
          where date_sk = d_date_sk
            and d_date between cast('2000-08-23' as date)
                           and cast('2000-08-23' as date) + interval '14' day
            and page_sk = cp_catalog_page_sk
          group by cp_catalog_page_id) csr
    union all
    select 'web channel' as channel, 'web_site' || web_site_id as id,
           sales, returns_, profit - profit_loss as profit
    from (select web_site_id,
                 sum(sales_price) as sales, sum(profit) as profit,
                 sum(return_amt) as returns_, sum(net_loss) as profit_loss
          from (select ws_web_site_sk as wsr_web_site_sk,
                       ws_sold_date_sk as date_sk,
                       ws_ext_sales_price as sales_price,
                       ws_net_profit as profit,
                       cast(0 as double) as return_amt,
                       cast(0 as double) as net_loss
                from web_sales
                union all
                select ws_web_site_sk, wr_returned_date_sk,
                       cast(0 as double), cast(0 as double),
                       wr_return_amt, wr_net_loss
                from web_returns
                left outer join web_sales
                  on (wr_item_sk = ws_item_sk
                      and wr_order_number = ws_order_number)) salesreturns,
               date_dim, web_site
          where date_sk = d_date_sk
            and d_date between cast('2000-08-23' as date)
                           and cast('2000-08-23' as date) + interval '14' day
            and wsr_web_site_sk = web_site_sk
          group by web_site_id) wsr
"""

_Q77_BODY = """
    select 'store channel' as channel, ss.s_store_sk as id, sales,
           coalesce(returns_, 0) as returns_,
           profit - coalesce(profit_loss, 0) as profit
    from (select s_store_sk, sum(ss_ext_sales_price) as sales,
                 sum(ss_net_profit) as profit
          from store_sales, date_dim, store
          where ss_sold_date_sk = d_date_sk
            and d_date between cast('2000-08-23' as date)
                           and cast('2000-08-23' as date) + interval '30' day
            and ss_store_sk = s_store_sk
          group by s_store_sk) ss
    left join (select s_store_sk, sum(sr_return_amt) as returns_,
                      sum(sr_net_loss) as profit_loss
               from store_returns, date_dim, store
               where sr_returned_date_sk = d_date_sk
                 and d_date between cast('2000-08-23' as date)
                                and cast('2000-08-23' as date) + interval '30' day
                 and sr_store_sk = s_store_sk
               group by s_store_sk) sr
      on ss.s_store_sk = sr.s_store_sk
    union all
    select 'catalog channel' as channel, cs_call_center_sk as id, sales,
           returns_, profit - profit_loss as profit
    from (select cs_call_center_sk, sum(cs_ext_sales_price) as sales,
                 sum(cs_net_profit) as profit
          from catalog_sales, date_dim
          where cs_sold_date_sk = d_date_sk
            and d_date between cast('2000-08-23' as date)
                           and cast('2000-08-23' as date) + interval '30' day
          group by cs_call_center_sk) cs,
         (select sum(cr_return_amount) as returns_,
                 sum(cr_net_loss) as profit_loss
          from catalog_returns, date_dim
          where cr_returned_date_sk = d_date_sk
            and d_date between cast('2000-08-23' as date)
                           and cast('2000-08-23' as date) + interval '30' day) cr
    union all
    select 'web channel' as channel, ws.wp_web_page_sk as id, sales,
           coalesce(returns_, 0) as returns_,
           profit - coalesce(profit_loss, 0) as profit
    from (select wp_web_page_sk, sum(ws_ext_sales_price) as sales,
                 sum(ws_net_profit) as profit
          from web_sales, date_dim, web_page
          where ws_sold_date_sk = d_date_sk
            and d_date between cast('2000-08-23' as date)
                           and cast('2000-08-23' as date) + interval '30' day
            and ws_web_page_sk = wp_web_page_sk
          group by wp_web_page_sk) ws
    left join (select wp_web_page_sk, sum(wr_return_amt) as returns_,
                      sum(wr_net_loss) as profit_loss
               from web_returns, date_dim, web_page
               where wr_returned_date_sk = d_date_sk
                 and d_date between cast('2000-08-23' as date)
                                and cast('2000-08-23' as date) + interval '30' day
                 and wr_web_page_sk = wp_web_page_sk
               group by wp_web_page_sk) wr
      on ws.wp_web_page_sk = wr.wp_web_page_sk
"""

_Q80_BODY = """
    select 'store channel' as channel, 'store' || store_id as id,
           sales, returns_, profit
    from (select s_store_id as store_id, sum(ss_ext_sales_price) as sales,
                 sum(coalesce(sr_return_amt, 0)) as returns_,
                 sum(ss_net_profit - coalesce(sr_net_loss, 0)) as profit
          from store_sales
          left outer join store_returns
            on (ss_item_sk = sr_item_sk
                and ss_ticket_number = sr_ticket_number),
          date_dim, store, item, promotion
          where ss_sold_date_sk = d_date_sk
            and d_date between cast('2000-08-23' as date)
                           and cast('2000-08-23' as date) + interval '30' day
            and ss_store_sk = s_store_sk
            and ss_item_sk = i_item_sk
            and i_current_price > 50
            and ss_promo_sk = p_promo_sk
            and p_channel_tv = 'N'
          group by s_store_id) ssr
    union all
    select 'catalog channel' as channel,
           'catalog_page' || catalog_page_id as id, sales, returns_, profit
    from (select cp_catalog_page_id as catalog_page_id,
                 sum(cs_ext_sales_price) as sales,
                 sum(coalesce(cr_return_amount, 0)) as returns_,
                 sum(cs_net_profit - coalesce(cr_net_loss, 0)) as profit
          from catalog_sales
          left outer join catalog_returns
            on (cs_item_sk = cr_item_sk and cs_order_number = cr_order_number),
          date_dim, catalog_page, item, promotion
          where cs_sold_date_sk = d_date_sk
            and d_date between cast('2000-08-23' as date)
                           and cast('2000-08-23' as date) + interval '30' day
            and cs_catalog_page_sk = cp_catalog_page_sk
            and cs_item_sk = i_item_sk
            and i_current_price > 50
            and cs_promo_sk = p_promo_sk
            and p_channel_tv = 'N'
          group by cp_catalog_page_id) csr
    union all
    select 'web channel' as channel, 'web_site' || web_site_id as id,
           sales, returns_, profit
    from (select web_site_id, sum(ws_ext_sales_price) as sales,
                 sum(coalesce(wr_return_amt, 0)) as returns_,
                 sum(ws_net_profit - coalesce(wr_net_loss, 0)) as profit
          from web_sales
          left outer join web_returns
            on (ws_item_sk = wr_item_sk and ws_order_number = wr_order_number),
          date_dim, web_site, item, promotion
          where ws_sold_date_sk = d_date_sk
            and d_date between cast('2000-08-23' as date)
                           and cast('2000-08-23' as date) + interval '30' day
            and ws_web_site_sk = web_site_sk
            and ws_item_sk = i_item_sk
            and i_current_price > 50
            and ws_promo_sk = p_promo_sk
            and p_channel_tv = 'N'
          group by web_site_id) wsr
"""

_Q18_CORE = """
from catalog_sales, customer_demographics cd1, customer_demographics cd2,
     customer, customer_address, date_dim, item
where cs_sold_date_sk = d_date_sk
  and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd1.cd_demo_sk
  and cs_bill_customer_sk = c_customer_sk
  and cd1.cd_gender = 'F'
  and cd1.cd_education_status = 'Unknown'
  and c_current_cdemo_sk = cd2.cd_demo_sk
  and c_current_addr_sk = ca_address_sk
  and c_birth_month in (1, 6, 8, 9, 12, 2)
  and d_year = 1998
  and ca_state in ('MS', 'IN', 'ND', 'OK', 'NM', 'VA', 'MS')
"""

_Q18_AGGS = """
       avg(cast(cs_quantity as double)) as agg1,
       avg(cast(cs_list_price as double)) as agg2,
       avg(cast(cs_coupon_amt as double)) as agg3,
       avg(cast(cs_sales_price as double)) as agg4,
       avg(cast(cs_net_profit as double)) as agg5,
       avg(cast(c_birth_year as double)) as agg6,
       avg(cast(cd1.cd_dep_count as double)) as agg7
"""

_Q22_CORE = """
from inventory, date_dim, item
where inv_date_sk = d_date_sk
  and inv_item_sk = i_item_sk
  and d_month_seq between 1200 and 1200 + 11
"""

_Q27_CORE = """
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk
  and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M'
  and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and d_year = 2002
  and s_state = 'TN'
"""

_Q27_AGGS = """
       avg(ss_quantity) as agg1, avg(ss_list_price) as agg2,
       avg(ss_coupon_amt) as agg3, avg(ss_sales_price) as agg4
"""

_Q36_CORE = """
from store_sales, date_dim d1, item, store
where d1.d_year = 2001
  and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and s_state = 'TN'
"""

_Q67_CORE = """
from store_sales, date_dim, store, item
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk
  and d_month_seq between 1200 and 1200 + 11
"""

_Q70_CORE = """
from store_sales, date_dim d1, store
where d1.d_month_seq between 1200 and 1200 + 11
  and d1.d_date_sk = ss_sold_date_sk
  and s_store_sk = ss_store_sk
  and s_state in (select s_state
                  from (select s_state,
                               rank() over (partition by s_state
                                            order by sum(ss_net_profit) desc) as ranking
                        from store_sales, store, date_dim
                        where d_month_seq between 1200 and 1200 + 11
                          and d_date_sk = ss_sold_date_sk
                          and s_store_sk = ss_store_sk
                        group by s_state) tmp1
                  where ranking <= 5)
"""

_Q86_CORE = """
from web_sales, date_dim d1, item
where d1.d_month_seq between 1200 and 1200 + 11
  and d1.d_date_sk = ws_sold_date_sk
  and i_item_sk = ws_item_sk
"""


def _rollup_levels(keys, select_aggs, core, extra_cols_fn=None):
    """Plain-SQL rollup: one SELECT per level, masked keys as NULL."""
    parts = []
    for level in range(len(keys), -1, -1):
        cols = []
        for i, k in enumerate(keys):
            cols.append(f"{k[1]} as {k[0]}" if i < level else f"null as {k[0]}")
        extra = extra_cols_fn(level) if extra_cols_fn else ""
        group = ", ".join(k[1] for k in keys[:level])
        group_clause = f"group by {group}" if group else ""
        parts.append(
            f"select {', '.join(cols)}{extra}, {select_aggs} {core} {group_clause}"
        )
    return "\nunion all\n".join(parts)


_Q14_CTES = """
with cross_items as (
    select i_item_sk as ss_item_sk
    from item,
         (select iss.i_brand_id as brand_id, iss.i_class_id as class_id,
                 iss.i_category_id as category_id
          from store_sales, item iss, date_dim d1
          where ss_item_sk = iss.i_item_sk
            and ss_sold_date_sk = d1.d_date_sk
            and d1.d_year between 1999 and 1999 + 2
          intersect
          select ics.i_brand_id, ics.i_class_id, ics.i_category_id
          from catalog_sales, item ics, date_dim d2
          where cs_item_sk = ics.i_item_sk
            and cs_sold_date_sk = d2.d_date_sk
            and d2.d_year between 1999 and 1999 + 2
          intersect
          select iws.i_brand_id, iws.i_class_id, iws.i_category_id
          from web_sales, item iws, date_dim d3
          where ws_item_sk = iws.i_item_sk
            and ws_sold_date_sk = d3.d_date_sk
            and d3.d_year between 1999 and 1999 + 2) x
    where i_brand_id = brand_id
      and i_class_id = class_id
      and i_category_id = category_id
), avg_sales as (
    select avg(quantity * list_price) as average_sales
    from (select ss_quantity as quantity, ss_list_price as list_price
          from store_sales, date_dim
          where ss_sold_date_sk = d_date_sk
            and d_year between 1999 and 1999 + 2
          union all
          select cs_quantity as quantity, cs_list_price as list_price
          from catalog_sales, date_dim
          where cs_sold_date_sk = d_date_sk
            and d_year between 1999 and 1999 + 2
          union all
          select ws_quantity as quantity, ws_list_price as list_price
          from web_sales, date_dim
          where ws_sold_date_sk = d_date_sk
            and d_year between 1999 and 1999 + 2) x
)
"""

_Q14_Y = """
    select 'store' as channel, i_brand_id, i_class_id, i_category_id,
           sum(ss_quantity * ss_list_price) as sales,
           count(*) as number_sales
    from store_sales, item, date_dim
    where ss_item_sk in (select ss_item_sk from cross_items)
      and ss_item_sk = i_item_sk
      and ss_sold_date_sk = d_date_sk
      and d_year = 1999 + 2 and d_moy = 11
    group by i_brand_id, i_class_id, i_category_id
    having sum(ss_quantity * ss_list_price)
           > (select average_sales from avg_sales)
    union all
    select 'catalog' as channel, i_brand_id, i_class_id, i_category_id,
           sum(cs_quantity * cs_list_price) as sales,
           count(*) as number_sales
    from catalog_sales, item, date_dim
    where cs_item_sk in (select ss_item_sk from cross_items)
      and cs_item_sk = i_item_sk
      and cs_sold_date_sk = d_date_sk
      and d_year = 1999 + 2 and d_moy = 11
    group by i_brand_id, i_class_id, i_category_id
    having sum(cs_quantity * cs_list_price)
           > (select average_sales from avg_sales)
    union all
    select 'web' as channel, i_brand_id, i_class_id, i_category_id,
           sum(ws_quantity * ws_list_price) as sales,
           count(*) as number_sales
    from web_sales, item, date_dim
    where ws_item_sk in (select ss_item_sk from cross_items)
      and ws_item_sk = i_item_sk
      and ws_sold_date_sk = d_date_sk
      and d_year = 1999 + 2 and d_moy = 11
    group by i_brand_id, i_class_id, i_category_id
    having sum(ws_quantity * ws_list_price)
           > (select average_sales from avg_sales)
"""


def _q14_equiv() -> str:
    keys = ["channel", "i_brand_id", "i_class_id", "i_category_id"]
    parts = []
    for level in range(len(keys), -1, -1):
        cols = ", ".join(
            k if i < level else f"null as {k}" for i, k in enumerate(keys)
        )
        grp = ", ".join(keys[:level])
        grp_clause = f"group by {grp}" if grp else ""
        parts.append(
            f"select {cols}, sum(sales) as sum_sales,"
            f" sum(number_sales) as sum_number_sales from ({_Q14_Y}) y"
            f" {grp_clause}"
        )
    return (
        _Q14_CTES
        + "\nunion all\n".join(parts)
        + "\norder by channel nulls last, i_brand_id nulls last,"
        " i_class_id nulls last, i_category_id nulls last\nlimit 100\n"
    )


EQUIV = {
    5: _channel_id_rollup(_Q5_BODY),
    14: _q14_equiv(),
    77: _channel_id_rollup(_Q77_BODY),
    80: _channel_id_rollup(_Q80_BODY),
    18: f"""
select i_item_id, ca_country, ca_state, ca_county, agg1, agg2, agg3, agg4,
       agg5, agg6, agg7
from (
{_rollup_levels(
    [("i_item_id", "i_item_id"), ("ca_country", "ca_country"),
     ("ca_state", "ca_state"), ("ca_county", "ca_county")],
    _Q18_AGGS.strip(), _Q18_CORE)}
) t
order by ca_country, ca_state, ca_county, i_item_id
limit 100
""",
    22: f"""
select i_product_name, i_brand, i_class, i_category, qoh
from (
{_rollup_levels(
    [("i_product_name", "i_product_name"), ("i_brand", "i_brand"),
     ("i_class", "i_class"), ("i_category", "i_category")],
    "avg(inv_quantity_on_hand) as qoh", _Q22_CORE)}
) t
order by qoh nulls last, i_product_name nulls last, i_brand nulls last,
         i_class nulls last, i_category nulls last
limit 100
""",
    27: f"""
select i_item_id, s_state, g_state, agg1, agg2, agg3, agg4
from (
{_rollup_levels(
    [("i_item_id", "i_item_id"), ("s_state", "s_state")],
    _Q27_AGGS.strip(), _Q27_CORE,
    extra_cols_fn=lambda lvl: ", 0 as g_state" if lvl == 2 else ", 1 as g_state")}
) t
order by i_item_id, s_state
limit 100
""",
    36: f"""
select gross_margin, i_category, i_class, lochierarchy, rank_within_parent
from (
    select gross_margin, i_category, i_class, lochierarchy,
           rank() over (partition by lochierarchy, parent_key
                        order by gross_margin asc) as rank_within_parent
    from (
{_rollup_levels(
    [("i_category", "i_category"), ("i_class", "i_class")],
    "sum(ss_net_profit) / sum(ss_ext_sales_price) as gross_margin",
    _Q36_CORE,
    extra_cols_fn=lambda lvl: (
        ", 0 as lochierarchy, i_category as parent_key" if lvl == 2
        else ", 1 as lochierarchy, null as parent_key" if lvl == 1
        else ", 2 as lochierarchy, null as parent_key"))}
    ) base
) t
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end,
         rank_within_parent
limit 100
""",
    67: f"""
select *
from (select i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
             d_moy, s_store_id, sumsales,
             rank() over (partition by i_category
                          order by sumsales desc) as rk
      from (
{_rollup_levels(
    [("i_category", "i_category"), ("i_class", "i_class"),
     ("i_brand", "i_brand"), ("i_product_name", "i_product_name"),
     ("d_year", "d_year"), ("d_qoy", "d_qoy"), ("d_moy", "d_moy"),
     ("s_store_id", "s_store_id")],
    "sum(coalesce(ss_sales_price * ss_quantity, 0)) as sumsales",
    _Q67_CORE)}
      ) dw1) dw2
where rk <= 100
order by i_category nulls last, i_class nulls last, i_brand nulls last,
         i_product_name nulls last, d_year nulls last, d_qoy nulls last,
         d_moy nulls last, s_store_id nulls last, sumsales nulls last,
         rk nulls last
limit 100
""",
    70: f"""
select total_sum, s_state, s_county, lochierarchy, rank_within_parent
from (
    select total_sum, s_state, s_county, lochierarchy,
           rank() over (partition by lochierarchy, parent_key
                        order by total_sum desc) as rank_within_parent
    from (
{_rollup_levels(
    [("s_state", "s_state"), ("s_county", "s_county")],
    "sum(ss_net_profit) as total_sum", _Q70_CORE,
    extra_cols_fn=lambda lvl: (
        ", 0 as lochierarchy, s_state as parent_key" if lvl == 2
        else ", 1 as lochierarchy, null as parent_key" if lvl == 1
        else ", 2 as lochierarchy, null as parent_key"))}
    ) base
) t
order by lochierarchy desc,
         case when lochierarchy = 0 then s_state end,
         rank_within_parent
limit 100
""",
    86: f"""
select total_sum, i_category, i_class, lochierarchy, rank_within_parent
from (
    select total_sum, i_category, i_class, lochierarchy,
           rank() over (partition by lochierarchy, parent_key
                        order by total_sum desc) as rank_within_parent
    from (
{_rollup_levels(
    [("i_category", "i_category"), ("i_class", "i_class")],
    "sum(ws_net_paid) as total_sum", _Q86_CORE,
    extra_cols_fn=lambda lvl: (
        ", 0 as lochierarchy, i_category as parent_key" if lvl == 2
        else ", 1 as lochierarchy, null as parent_key" if lvl == 1
        else ", 2 as lochierarchy, null as parent_key"))}
    ) base
) t
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end,
         rank_within_parent
limit 100
""",
}
