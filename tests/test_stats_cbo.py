"""Cost-based optimizer statistics tests (reference: TestFilterStatsCalculator,
TestJoinStatsRule, TestReorderJoins in core/trino-main/src/test/.../cost/)."""

import pytest

from trino_tpu.planner import plan as P
from trino_tpu.planner.stats import ColStats, PlanStats, compute_stats, filter_stats
from trino_tpu.expr.ir import Call, Form, Literal, SpecialForm, SymbolRef
from trino_tpu import types as T

pytestmark = pytest.mark.smoke


def _sym(name):
    return SymbolRef(name, T.BIGINT)


def _lit(v):
    return Literal(v, T.BIGINT)


def test_equality_selectivity_uses_ndv():
    st = PlanStats(1000.0, {"x": ColStats(ndv=50.0, low=0, high=49)})
    out = filter_stats(st, Call("$eq", [_sym("x"), _lit(7)], T.BOOLEAN))
    assert out.rows == pytest.approx(20.0)
    assert out.col("x").ndv == 1.0


def test_range_selectivity_from_min_max():
    st = PlanStats(1000.0, {"x": ColStats(ndv=100.0, low=0.0, high=100.0)})
    out = filter_stats(st, Call("$lt", [_sym("x"), _lit(25)], T.BOOLEAN))
    assert out.rows == pytest.approx(250.0)
    assert out.col("x").high == 25.0


def test_between_and_in_selectivity():
    st = PlanStats(1000.0, {"x": ColStats(ndv=100.0, low=0.0, high=100.0)})
    btw = SpecialForm(Form.BETWEEN, [_sym("x"), _lit(10), _lit(30)], T.BOOLEAN)
    assert filter_stats(st, btw).rows == pytest.approx(200.0)
    inl = SpecialForm(Form.IN, [_sym("x"), _lit(1), _lit(2), _lit(3)], T.BOOLEAN)
    assert filter_stats(st, inl).rows == pytest.approx(30.0)


def test_or_inclusion_exclusion():
    st = PlanStats(1000.0, {"x": ColStats(ndv=10.0)})
    disj = SpecialForm(
        Form.OR,
        [
            Call("$eq", [_sym("x"), _lit(1)], T.BOOLEAN),
            Call("$eq", [_sym("x"), _lit(2)], T.BOOLEAN),
        ],
        T.BOOLEAN,
    )
    # 0.1 + 0.1 - 0.01 = 0.19
    assert filter_stats(st, disj).rows == pytest.approx(190.0)


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime.runner import LocalQueryRunner

    return LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=2)


def test_join_output_uses_key_ndv(runner):
    """orders JOIN lineitem on orderkey ~ |lineitem| rows, not |o|*|l|."""
    from trino_tpu.planner.stats import estimate_rows

    plan = runner.create_plan(
        "select * from orders o, lineitem l where o.o_orderkey = l.l_orderkey"
    )
    rows = estimate_rows(plan, runner.catalogs)
    li = runner.catalogs.get("tpch").metadata().table_statistics(
        "tiny", "lineitem"
    ).row_count
    assert rows == pytest.approx(li, rel=0.3)


def test_join_order_small_build_side(runner):
    """region (5 rows) must be a build (right) side, never the probe spine."""
    plan = runner.create_plan(
        "select n_name from nation, region "
        "where n_regionkey = r_regionkey and r_name = 'ASIA'"
    )

    joins = []

    def walk(n):
        if isinstance(n, P.JoinNode):
            joins.append(n)
        for c in n.children:
            walk(c)

    walk(plan)
    assert joins, "expected a join in the plan"
    j = joins[0]
    left = compute_stats(j.left, runner.catalogs).rows
    right = compute_stats(j.right, runner.catalogs).rows
    assert right <= left


def test_show_stats(runner):
    res = runner.execute("show stats for lineitem")
    cols = {r[0]: r for r in res.rows}
    assert None in cols  # summary row
    assert cols[None][4] is not None and cols[None][4] > 0  # row_count
    lq = cols["l_quantity"]
    assert lq[2] == pytest.approx(50.0)  # ndv
    assert float(lq[5]) == 1.0 and float(lq[6]) == 50.0


def test_show_stats_memory_exact(runner):
    runner.execute("create table memory.default.st (a bigint, b double)")
    runner.execute(
        "insert into memory.default.st values (1, 1.5), (2, 2.5), (2, null)"
    )
    res = runner.execute("show stats for memory.default.st")
    cols = {r[0]: r for r in res.rows}
    assert cols["a"][2] == pytest.approx(2.0)  # ndv {1,2}
    assert cols["b"][3] == pytest.approx(1.0 / 3.0)  # null fraction
    assert cols[None][4] == pytest.approx(3.0)
