"""GROUPING SETS / ROLLUP / CUBE (reference: SqlBase.g4:273-275
groupingElement, sql/planner/plan/GroupIdNode.java, QueryPlanner
.planGroupingSets).  Oracle: pandas per-set groupby + concat — grouping sets
are exactly a union of per-set aggregations."""

import numpy as np
import pandas as pd
import pytest

pytestmark = pytest.mark.smoke

from tests.test_e2e import assert_rows_match
from trino_tpu.runtime.runner import LocalQueryRunner
from trino_tpu.testing import tpch_pandas


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=2)


@pytest.fixture(scope="module")
def nation():
    return tpch_pandas("tiny", "nation")


def _per_set_counts(df, all_keys, sets, value_col, how):
    """Expected rows: for each grouping set, aggregate with its keys
    (non-member key columns NULL) — the definition of grouping sets."""
    out = []
    for s in sets:
        if s:
            g = df.groupby(list(s))[value_col]
            agg = (g.size() if how == "count" else getattr(g, how)()).reset_index(
                name="v"
            )
            for _, row in agg.iterrows():
                out.append(
                    tuple(row[k] if k in s else None for k in all_keys)
                    + (row["v"],)
                )
        else:
            v = len(df) if how == "count" else getattr(df[value_col], how)()
            out.append((None,) * len(all_keys) + (v,))
    return out


def test_rollup(runner, nation):
    df = nation.assign(g=nation.n_nationkey % 3)
    sets = [("n_regionkey", "g"), ("n_regionkey",), ()]
    exp = _per_set_counts(df, ("n_regionkey", "g"), sets, "n_nationkey", "count")
    got = runner.execute(
        "select n_regionkey, n_nationkey%3 as g, count(*) c "
        "from nation group by rollup(n_regionkey, n_nationkey%3)"
    ).rows
    assert_rows_match(got, exp, ordered=False)


def test_cube(runner, nation):
    df = nation.assign(g=nation.n_nationkey % 2)
    sets = [("n_regionkey", "g"), ("n_regionkey",), ("g",), ()]
    exp = _per_set_counts(df, ("n_regionkey", "g"), sets, "n_nationkey", "sum")
    got = runner.execute(
        "select n_regionkey, n_nationkey%2 as g, sum(n_nationkey) s "
        "from nation group by cube(n_regionkey, n_nationkey%2)"
    ).rows
    assert_rows_match(got, exp, ordered=False)


def test_grouping_sets_explicit_with_varchar_key(runner, nation):
    got = runner.execute(
        "select n_name, n_regionkey, count(*) c from nation "
        "group by grouping sets ((n_name, n_regionkey), (n_regionkey), ())"
    ).rows
    exp = []
    for _, row in nation.groupby(["n_name", "n_regionkey"]).size().reset_index(
        name="c"
    ).iterrows():
        exp.append((row.n_name, row.n_regionkey, row.c))
    for _, row in nation.groupby("n_regionkey").size().reset_index(name="c").iterrows():
        exp.append((None, row.n_regionkey, row.c))
    exp.append((None, None, len(nation)))
    assert_rows_match(got, exp, ordered=False)


def test_grouping_function(runner):
    got = runner.execute(
        "select n_regionkey, grouping(n_regionkey) g, count(*) c "
        "from nation group by rollup(n_regionkey) order by g, n_regionkey"
    ).rows
    # 5 regions with grouping()=0, one total row with grouping()=1
    assert got[-1][1] == 1 and got[-1][2] == 25
    assert all(r[1] == 0 for r in got[:-1])
    assert sum(r[2] for r in got[:-1]) == 25


def test_grouping_bitmask_order(runner):
    rows = runner.execute(
        "select n_regionkey, n_nationkey%2 as g, "
        "grouping(n_regionkey, n_nationkey%2) gm, count(*) c "
        "from nation group by grouping sets ((n_regionkey), (n_nationkey%2))"
    ).rows
    # set (n_regionkey): second arg ungrouped -> mask 0b01; set (g): 0b10
    masks = {r[2] for r in rows}
    assert masks == {1, 2}
    for r in rows:
        if r[2] == 1:
            assert r[1] is None and r[0] is not None
        else:
            assert r[0] is None and r[1] is not None


def test_rollup_with_having_on_grouping(runner):
    rows = runner.execute(
        "select n_regionkey, count(*) c from nation "
        "group by rollup(n_regionkey) having grouping(n_regionkey) = 1"
    ).rows
    assert rows == [(None, 25)]


def test_group_by_mixed_plain_and_rollup(runner, nation):
    # GROUP BY a, ROLLUP(b) = sets {(a,b), (a)}
    df = nation.assign(g=nation.n_nationkey % 2)
    got = runner.execute(
        "select n_regionkey, n_nationkey%2 as g, count(*) c "
        "from nation group by n_regionkey, rollup(n_nationkey%2)"
    ).rows
    exp = []
    for _, row in df.groupby(["n_regionkey", "g"]).size().reset_index(
        name="c"
    ).iterrows():
        exp.append((row.n_regionkey, row.g, row.c))
    for _, row in df.groupby("n_regionkey").size().reset_index(name="c").iterrows():
        exp.append((row.n_regionkey, None, row.c))
    assert_rows_match(got, exp, ordered=False)


def test_rollup_avg_and_multiple_aggs(runner, nation):
    got = runner.execute(
        "select n_regionkey, count(*) c, sum(n_nationkey) s, "
        "avg(n_nationkey) a from nation group by rollup(n_regionkey)"
    ).rows
    df = nation
    exp = []
    for _, row in (
        df.groupby("n_regionkey")
        .agg(c=("n_nationkey", "size"), s=("n_nationkey", "sum"), a=("n_nationkey", "mean"))
        .reset_index()
        .iterrows()
    ):
        exp.append((row.n_regionkey, row.c, row.s, row.a))
    exp.append((None, len(df), df.n_nationkey.sum(), df.n_nationkey.mean()))
    assert_rows_match(got, exp, ordered=False)
