"""MATCH_RECOGNIZE tests (reference: the SQL-2016 row pattern examples used
by TestRowPatternMatching.java — V-shape stock patterns, quantifiers,
classifier/match_number, skip modes)."""

import pytest

pytestmark = pytest.mark.smoke


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime.runner import LocalQueryRunner

    r = LocalQueryRunner(catalog="memory", schema="default", target_splits=2)
    r.execute("create table stock (sym varchar, day bigint, price double)")
    r.execute(
        "insert into stock values "
        "('A', 1, 10), ('A', 2, 8), ('A', 3, 6), ('A', 4, 9), ('A', 5, 12), "
        "('A', 6, 11), ('B', 1, 5), ('B', 2, 6), ('B', 3, 4), ('B', 4, 7)"
    )
    return r


V_QUERY = """
select * from stock match_recognize (
  partition by sym
  order by day
  measures first(price) as strt,
           min(down.price) as bottom,
           last(up.price) as top,
           match_number() as mno
  one row per match
  after match skip past last row
  pattern (strt down+ up+)
  define down as price < prev(price),
         up as price > prev(price)
)
"""


def test_v_shape_one_row_per_match(runner):
    rows = sorted(runner.execute(V_QUERY).rows)
    # MATCH_NUMBER() restarts per partition (SQL-2016)
    assert rows == [("A", 10.0, 6.0, 12.0, 1), ("B", 6.0, 4.0, 7.0, 1)]


def test_all_rows_per_match_classifier(runner):
    rows = runner.execute(
        """
        select sym, day, cls from stock match_recognize (
          partition by sym order by day
          measures classifier() as cls
          all rows per match
          pattern (strt down+ up+)
          define down as price < prev(price),
                 up as price > prev(price)
        ) where sym = 'A' order by day
        """
    ).rows
    assert rows == [
        ("A", 1, "strt"), ("A", 2, "down"), ("A", 3, "down"),
        ("A", 4, "up"), ("A", 5, "up"),
    ]


def test_skip_to_next_row(runner):
    rows = runner.execute(
        """
        select cnt from stock match_recognize (
          partition by sym order by day
          measures count(*) as cnt
          one row per match
          after match skip to next row
          pattern (down down)
          define down as price < prev(price)
        )
        """
    ).rows
    # A: days 2,3 both falling -> overlapping matches at day2 start only
    assert rows == [(2,)]


def test_quantifier_bounds(runner):
    rows = runner.execute(
        """
        select mno, cnt from stock match_recognize (
          partition by sym order by day
          measures match_number() as mno, count(*) as cnt
          pattern (down{2})
          define down as price < prev(price)
        )
        """
    ).rows
    assert rows == [(1, 2)]  # exactly-two falling days (A: days 2-3)


def test_alternation(runner):
    rows = sorted(
        runner.execute(
            """
            select sym, cls from stock match_recognize (
              partition by sym order by day
              measures classifier() as cls
              pattern (big | small)
              define big as price >= 10,
                     small as price <= 4
            )
            """
        ).rows
    )
    # leftmost rows matching either: A day1 (10 -> big), B day3 (4 -> small)
    assert ("A", "big") in rows and ("B", "small") in rows


def test_undefined_variable_matches_any(runner):
    rows = runner.execute(
        """
        select cnt from stock match_recognize (
          partition by sym order by day
          measures count(*) as cnt
          pattern (anyrow down)
          define down as price < prev(price)
        ) order by 1
        """
    ).rows
    assert len(rows) >= 1


def test_explain_contains_pattern_node(runner):
    txt = runner.execute("explain " + V_QUERY).rows
    flat = "\n".join(r[0] for r in txt)
    assert "PatternRecognition" in flat


def test_string_measure_decodes(runner):
    rows = runner.execute(
        """
        select s from stock match_recognize (
          partition by sym order by day
          measures last(sym) as s
          pattern (down+)
          define down as price < prev(price)
        ) order by 1
        """
    ).rows
    # A falls on days 2-3 and again on day 6; B falls on day 3
    assert rows == [("A",), ("A",), ("B",)]


def test_next_navigation_last_row_null(runner):
    # NEXT at the final row of a partition must be NULL, never a padded row
    rows = runner.execute(
        """
        select cnt from stock match_recognize (
          partition by sym order by day
          measures count(*) as cnt
          pattern (tail)
          define tail as next(price) is null and price > 10
        )
        """
    ).rows
    assert rows == [(1,)]  # only A day6 (11 > 10, last of partition)


def test_first_offset(runner):
    rows = runner.execute(
        """
        select p from stock match_recognize (
          partition by sym order by day
          measures first(price, 1) as p
          pattern (down down)
          define down as price < prev(price)
        )
        """
    ).rows
    assert rows == [(6.0,)]  # second DOWN row of A's (8, 6) run


def test_cross_variable_define_rejected(runner):
    with __import__("pytest").raises(Exception, match="cross-variable"):
        runner.execute(
            """
            select mno from stock match_recognize (
              partition by sym order by day
              measures match_number() as mno
              pattern (strt up)
              define up as up.price > strt.price
            )
            """
        )
