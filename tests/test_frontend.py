"""Parser + analyzer/logical-planner tests (reference: trino-parser tests +
BasePlanTest plan-shape assertions)."""

import pytest

pytestmark = pytest.mark.smoke

from trino_tpu.connectors.api import default_catalogs
from trino_tpu.connectors.tpch.queries import QUERIES
from trino_tpu.planner import plan as P
from trino_tpu.planner.analyzer import AnalysisError
from trino_tpu.planner.logical_planner import LogicalPlanner, Session
from trino_tpu.planner.plan import plan_text, walk
from trino_tpu.sql import ast
from trino_tpu.sql.parser import ParseError, parse_statement


@pytest.fixture(scope="module")
def catalogs():
    return default_catalogs()


def _plan(sql, catalogs, schema="tiny"):
    stmt = parse_statement(sql)
    return LogicalPlanner(catalogs, Session("tpch", schema)).plan(stmt.query)


def test_parse_all_tpch():
    for qid, sql in QUERIES.items():
        stmt = parse_statement(sql)
        assert isinstance(stmt, ast.SelectStatement), f"Q{qid}"


def test_plan_all_tpch(catalogs):
    for qid, sql in QUERIES.items():
        out = _plan(sql, catalogs)
        assert isinstance(out, P.OutputNode), f"Q{qid}"
        assert plan_text(out)


def test_parse_errors():
    with pytest.raises(ParseError):
        parse_statement("select from where")
    with pytest.raises(ParseError):
        parse_statement("select 1 blah blah blah")
    with pytest.raises(ParseError):
        parse_statement("select * from t join u")  # missing ON/USING


def test_plan_shapes_q1(catalogs):
    out = _plan(QUERIES[1], catalogs)
    kinds = [type(n).__name__ for n in walk(out)]
    assert "AggregationNode" in kinds
    assert "TopNNode" in kinds or "SortNode" in kinds
    agg = next(n for n in walk(out) if isinstance(n, P.AggregationNode))
    assert len(agg.group_symbols) == 2
    assert len(agg.aggregations) == 8  # 4 sums + 3 avgs + count(*)


def test_plan_semi_join_q18(catalogs):
    out = _plan(QUERIES[18], catalogs)
    semis = [n for n in walk(out) if isinstance(n, P.SemiJoinNode)]
    assert len(semis) == 1


def test_plan_decorrelated_exists_q4(catalogs):
    out = _plan(QUERIES[4], catalogs)
    semis = [n for n in walk(out) if isinstance(n, P.SemiJoinNode)]
    assert len(semis) == 1
    assert semis[0].filter is None


def test_plan_q21_anti_and_semi(catalogs):
    out = _plan(QUERIES[21], catalogs)
    semis = [n for n in walk(out) if isinstance(n, P.SemiJoinNode)]
    assert len(semis) == 2
    assert all(s.filter is not None for s in semis)  # l_suppkey <> correlation


def test_plan_scalar_subquery_q17(catalogs):
    out = _plan(QUERIES[17], catalogs)
    joins = [n for n in walk(out) if isinstance(n, P.JoinNode) and n.kind == "left"]
    assert joins, "correlated scalar should become a LEFT join"
    aggs = [n for n in walk(out) if isinstance(n, P.AggregationNode)]
    assert any(len(a.group_symbols) == 1 for a in aggs)  # grouped by partkey


def test_error_messages(catalogs):
    with pytest.raises(AnalysisError, match="column not found"):
        _plan("select nope from lineitem", catalogs)
    with pytest.raises(AnalysisError, match="GROUP BY"):
        _plan("select l_orderkey, sum(l_quantity) from lineitem group by l_partkey",
              catalogs)
    with pytest.raises(KeyError, match="not found"):
        _plan("select * from nosuchtable", catalogs)
    with pytest.raises(AnalysisError, match="ambiguous"):
        _plan("select n_name from nation n1, nation n2", catalogs)


def test_order_by_alias_and_ordinal(catalogs):
    out = _plan(
        "select l_returnflag x, count(*) c from lineitem group by 1 order by c desc, 1",
        catalogs,
    )
    topn = [n for n in walk(out) if isinstance(n, (P.SortNode, P.TopNNode))]
    assert topn and len(topn[0].orderings) == 2
    assert topn[0].orderings[0][1] is False  # desc


def test_union_and_values(catalogs):
    out = _plan("select 1 x union all select 2", catalogs)
    assert any(isinstance(n, P.UnionNode) for n in walk(out))
    out = _plan("select * from (values (1, 'a'), (2, 'b')) t(id, name) where id > 1",
                catalogs)
    assert any(isinstance(n, P.ValuesNode) for n in walk(out))


def test_cte_planning(catalogs):
    out = _plan(
        "with r as (select l_suppkey k, sum(l_quantity) q from lineitem group by l_suppkey) "
        "select * from r where q > 100", catalogs)
    assert any(isinstance(n, P.AggregationNode) for n in walk(out))


def test_parser_no_hang_on_malformed(catalogs):
    with pytest.raises(ParseError):
        parse_statement("EXPLAIN (")
    with pytest.raises(ParseError):
        parse_statement("select sum(x) over (order by y rows unbounded")


def test_offset_plans_as_limit_node(catalogs):
    # OFFSET support landed in round 3: it plans as a LimitNode with offset
    out = _plan("select r_name from region offset 2", catalogs)
    assert any(
        isinstance(n, P.LimitNode) and n.offset == 2 for n in walk(out)
    )
    out = _plan(
        "select r_name from region order by r_name offset 2 limit 1", catalogs
    )
    assert any(
        isinstance(n, P.LimitNode) and n.offset == 2 and n.count == 1
        for n in walk(out)
    )


def test_scalar_count_subquery_coalesced(catalogs):
    out = _plan(
        "select c_custkey, (select count(*) from orders where o_custkey = c_custkey) n "
        "from customer", catalogs)
    from trino_tpu.expr.ir import SpecialForm as SF, Form as F
    projs = [n for n in walk(out) if isinstance(n, P.ProjectNode)]
    found = any(
        isinstance(e, SF) and e.form == F.COALESCE
        for p in projs for _, e in p.assignments
    )
    assert found


@pytest.mark.smoke
def test_explain_type_distributed():
    from trino_tpu.runtime.runner import LocalQueryRunner

    runner = LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=2)
    rows = runner.execute(
        "explain (type distributed) "
        "select l_returnflag, count(*) from lineitem group by 1"
    ).rows
    flat = "\n".join(r[0] for r in rows)
    assert "Fragment" in flat and "FIXED_HASH[l_returnflag]" in flat
    assert "RemoteSource" in flat
