"""LATERAL join tests (reference: sql/tree/Lateral.java + the
TransformCorrelated* decorrelation rules)."""

import pytest

pytestmark = pytest.mark.smoke


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime.runner import LocalQueryRunner

    return LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=2)


def test_lateral_projection_only(runner):
    rows = runner.execute(
        "select n_name, x from nation, lateral (select n_nationkey + 1 as x) "
        "where n_regionkey = 1 order by n_name limit 2"
    ).rows
    assert rows == [("ARGENTINA", 2), ("BRAZIL", 3)]


def test_lateral_correlated_aggregate(runner):
    rows = runner.execute(
        "select r_name, t.cnt from region, lateral "
        "(select count(*) cnt from nation where n_regionkey = r_regionkey) t "
        "order by r_name"
    ).rows
    assert rows == [(n, 5) for n, _ in rows]
    assert len(rows) == 5


def test_lateral_empty_group_count_zero(runner):
    rows = runner.execute(
        "select r_name, cnt from region, lateral "
        "(select count(*) cnt from nation "
        "where n_regionkey = r_regionkey and n_nationkey > 90) "
        "order by r_name limit 2"
    ).rows
    assert rows == [("AFRICA", 0), ("AMERICA", 0)]


def test_lateral_correlated_rows(runner):
    rows = runner.execute(
        "select r_name, n_name from region, lateral "
        "(select n_name from nation where n_regionkey = r_regionkey) "
        "order by r_name, n_name limit 3"
    ).rows
    assert rows == [
        ("AFRICA", "ALGERIA"), ("AFRICA", "ETHIOPIA"), ("AFRICA", "KENYA"),
    ]


def test_lateral_uncorrelated_aggregate_cross(runner):
    rows = runner.execute(
        "select r_name, x from region cross join lateral "
        "(select max(n_nationkey) x from nation) order by r_name limit 2"
    ).rows
    assert rows == [("AFRICA", 24), ("AMERICA", 24)]


def test_lateral_uncorrelated_limit(runner):
    rows = runner.execute(
        "select r_name, nn from region, lateral "
        "(select n_name nn from nation order by n_nationkey limit 2) "
        "order by r_name, nn limit 4"
    ).rows
    assert rows == [
        ("AFRICA", "ALGERIA"), ("AFRICA", "ARGENTINA"),
        ("AMERICA", "ALGERIA"), ("AMERICA", "ARGENTINA"),
    ]


def test_lateral_correlated_limit_rejected(runner):
    with pytest.raises(Exception, match="not found|LATERAL"):
        runner.execute(
            "select r_name, nn from region, lateral "
            "(select n_name nn from nation where n_regionkey = r_regionkey "
            "order by n_nationkey limit 1)"
        )


def test_lateral_star(runner):
    rows = runner.execute(
        "select r_name, n_name from region, lateral "
        "(select * from nation where n_regionkey = r_regionkey) "
        "order by r_name, n_name limit 2"
    ).rows
    assert rows == [("AFRICA", "ALGERIA"), ("AFRICA", "ETHIOPIA")]


def test_lateral_grouped_correlated_inner_semantics(runner):
    # user GROUP BY: empty groups drop the outer row (INNER, not LEFT)
    rows = runner.execute(
        "select r_name, c from region, lateral "
        "(select n_regionkey g, count(*) c from nation "
        "where n_regionkey = r_regionkey and n_nationkey > 20 "
        "group by n_regionkey) order by r_name"
    ).rows
    assert rows == [("AMERICA", 1), ("ASIA", 1), ("EUROPE", 2)]


def test_lateral_agg_with_limit_rejected(runner):
    with pytest.raises(Exception, match="ORDER BY/LIMIT"):
        runner.execute(
            "select r_name, c from region, lateral "
            "(select count(*) c from nation where n_regionkey = r_regionkey "
            "limit 1)"
        )


def test_outer_join_without_equi_clean_error(runner):
    with pytest.raises(Exception, match="equi-join condition"):
        runner.execute(
            "select r_name, x from region left join "
            "(select max(n_nationkey) x from nation) t on true"
        )
