"""Concurrent dispatcher + admission control (runtime/dispatcher).

Tier-1 serving tests: weighted-fair scheduling, per-group concurrency and
memory sub-pools, queue deadlines, load shedding (HTTP 429 + Retry-After
before the body is read), queued-query cancel racing admission, graceful
drain, spill release on abort, and the system.runtime.resource_groups SQL
surface.  Deterministic where possible (counter-driven clocks, events);
real timeouts kept to tens of milliseconds.  The HTTP-worker chaos
composition (worker kill at W-1 x pool shrink x K clients) lives in
tests/test_chaos.py (slow).
"""

import threading
import time

import pytest

from trino_tpu.runtime.dispatcher import (
    DispatcherStoppedError,
    QueryDispatcher,
    QueryShedError,
)
from trino_tpu.runtime.lifecycle import (
    QueryCanceledException,
    QueryQueuedTimeExceeded,
)
from trino_tpu.runtime.resource_groups import (
    GroupMemoryEscalation,
    ResourceGroupConfig,
    ResourceGroupManager,
)


class _DummyRunner:
    """Engine stand-in for scheduler-only tests: cloneable, no device."""

    def clone_for_dispatch(self):
        return _DummyRunner()


def _manager(*configs):
    mgr = ResourceGroupManager()
    for c in configs:
        mgr.add(c)
    return mgr


def _run_all(dispatcher, tickets, fn):
    """One thread per ticket: wait for admission, run fn(group_name)."""
    threads = []
    for t in tickets:
        def work(t=t):
            try:
                t.wait()
            except Exception:
                return
            dispatcher.run_admitted(t, lambda _r: fn(t.group_name))

        th = threading.Thread(target=work, daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=30)
        assert not th.is_alive(), "admission wait hung"


# -- weighted-fair scheduling --------------------------------------------------


def test_weighted_fair_ratio_across_saturated_groups():
    """Two saturated groups with weights 3:1 share one lane 3:1 — the
    scheduler picks by weighted virtual time, not round-robin or FIFO."""
    mgr = _manager(
        ResourceGroupConfig("a", hard_concurrency=1, weight=3),
        ResourceGroupConfig("b", hard_concurrency=1, weight=1),
    )
    d = QueryDispatcher(_DummyRunner(), mgr, lanes=1)
    gate = threading.Event()
    blocker = d.enqueue(group_name="global")
    blocker.wait()
    done = threading.Thread(
        target=lambda: d.run_admitted(blocker, lambda _r: gate.wait(10)),
        daemon=True,
    )
    done.start()
    tickets = []
    for _ in range(9):
        tickets.append(d.enqueue(group_name="a"))
    for _ in range(3):
        tickets.append(d.enqueue(group_name="b"))
    order = []
    lock = threading.Lock()

    def record(group):
        with lock:
            order.append(group)

    gate.set()  # release the lane: admissions begin
    _run_all(d, tickets, record)
    done.join(timeout=10)
    # single lane => execution order == admission order; first 8 picks
    # must honor the 3:1 weights (6 a's, 2 b's)
    assert order.count("a") == 9 and order.count("b") == 3
    first8 = order[:8]
    assert first8.count("a") == 6 and first8.count("b") == 2, order


def test_group_hard_concurrency_bounds_parallelism():
    mgr = _manager(ResourceGroupConfig("g", hard_concurrency=2, max_queued=16))
    d = QueryDispatcher(_DummyRunner(), mgr, lanes=4)
    peak = {"now": 0, "max": 0}
    lock = threading.Lock()

    def tracked(_group):
        with lock:
            peak["now"] += 1
            peak["max"] = max(peak["max"], peak["now"])
        time.sleep(0.02)
        with lock:
            peak["now"] -= 1

    tickets = [d.enqueue(group_name="g") for _ in range(6)]
    _run_all(d, tickets, tracked)
    assert peak["max"] == 2  # 4 lanes free, but the group caps at 2


def test_lanes_overlap_execution():
    """With 2 lanes, two admitted statements genuinely overlap (the old
    global engine lock could never pass this barrier)."""
    mgr = ResourceGroupManager(
        ResourceGroupConfig("global", hard_concurrency=2)
    )
    d = QueryDispatcher(_DummyRunner(), mgr, lanes=2)
    barrier = threading.Barrier(2, timeout=10)
    tickets = [d.enqueue() for _ in range(2)]
    _run_all(d, tickets, lambda _g: barrier.wait())
    assert not barrier.broken  # both statements were inside at once


# -- shedding + queue deadlines ------------------------------------------------


def test_full_queue_sheds_with_retry_after():
    from trino_tpu.telemetry.metrics import queries_shed_counter

    mgr = _manager(ResourceGroupConfig("g", hard_concurrency=1, max_queued=1))
    d = QueryDispatcher(_DummyRunner(), mgr, lanes=1)
    t1 = d.enqueue(group_name="g")  # runs
    d.enqueue(group_name="g")  # queues (1/1)
    shed0 = queries_shed_counter().value(("g",))
    with pytest.raises(QueryShedError) as ei:
        d.enqueue(group_name="g")  # queue full -> shed
    assert ei.value.retryable and ei.value.retry_after_s > 0
    assert ei.value.error_code == "QUERY_QUEUE_FULL"
    assert queries_shed_counter().value(("g",)) == shed0 + 1
    # shed_probe (the pre-body HTTP check) agrees while full
    mgr.add_user_rule("u", "g")
    assert d.shed_probe("u") is not None


def test_shed_probe_admits_when_idle_even_with_zero_queue():
    """max_queued=0 means 'never queue', not 'never run': an idle group
    admits immediately and the probe must not shed it."""
    mgr = _manager(ResourceGroupConfig("g", hard_concurrency=1, max_queued=0))
    mgr.add_user_rule("u", "g")
    d = QueryDispatcher(_DummyRunner(), mgr, lanes=1)
    assert d.shed_probe("u") is None
    t = d.enqueue(group_name="g")
    assert t.wait() is not None
    assert d.shed_probe("u") is not None  # slot held -> now it sheds
    d.release(t)


def test_queue_deadline_raises_exceeded_queued_time():
    from trino_tpu.telemetry.metrics import query_queued_histogram

    d = QueryDispatcher(_DummyRunner(), _manager(), lanes=1)
    blocker = d.enqueue()
    blocker.wait()
    n0 = query_queued_histogram().value()
    t = d.enqueue(queue_deadline_s=0.05)
    with pytest.raises(QueryQueuedTimeExceeded) as ei:
        t.wait()
    assert ei.value.error_code == "EXCEEDED_QUEUED_TIME_LIMIT"
    assert query_queued_histogram().value() == n0 + 1  # wait observed
    d.release(blocker)
    # the expired ticket left the queue: the group is clean
    assert d.stats()[0]["queued"] == 0 or all(
        s["queued"] == 0 for s in d.stats()
    )


# -- queued-query cancel -------------------------------------------------------


def test_cancel_while_queued_never_acquires_slot():
    d = QueryDispatcher(_DummyRunner(), _manager(), lanes=1)
    blocker = d.enqueue()
    blocker.wait()
    admitted_before = d.stats()[0]["total_admitted"]
    t = d.enqueue()
    t.cancel()
    with pytest.raises(QueryCanceledException):
        t.wait()
    d.release(blocker)
    # the canceled ticket was dequeued, not admitted
    stats = {s["name"]: s for s in d.stats()}
    assert stats["global"]["total_admitted"] == admitted_before
    assert stats["global"]["queued"] == 0


def test_cancel_racing_admission_hands_slot_back():
    """A DELETE that lands after the grant but before execution must hand
    the lane and group slot straight back — zero engine time consumed."""
    d = QueryDispatcher(_DummyRunner(), _manager(), lanes=1)
    t = d.enqueue()  # free lane: admitted synchronously
    t.cancel()
    with pytest.raises(QueryCanceledException):
        t.wait()
    stats = {s["name"]: s for s in d.stats()}
    assert stats["global"]["running"] == 0
    # the returned slot admits the next query immediately
    t2 = d.enqueue()
    assert t2.wait() is not None
    d.release(t2)


# -- drain ---------------------------------------------------------------------


def test_drain_fails_queued_classified_and_force_kills_running():
    d = QueryDispatcher(_DummyRunner(), _manager(), lanes=1)
    running_ev = threading.Event()
    blocker = d.enqueue()
    blocker.wait()
    blocker.on_force_kill = running_ev.set

    th = threading.Thread(
        target=lambda: d.run_admitted(
            blocker, lambda _r: running_ev.wait(10)
        ),
        daemon=True,
    )
    th.start()
    queued = d.enqueue()
    clean = d.drain(wait_s=0.05, grace_s=5.0)
    with pytest.raises(DispatcherStoppedError) as ei:
        queued.wait()
    assert ei.value.error_code == "SERVER_SHUTTING_DOWN"
    assert running_ev.is_set()  # force-kill reached the running statement
    assert clean  # ... and it released inside the grace window
    th.join(timeout=10)
    with pytest.raises(DispatcherStoppedError):
        d.enqueue()  # admission is closed for good


# -- legacy interop ------------------------------------------------------------


def test_legacy_release_wakes_queued_dispatcher_ticket():
    """A slot freed through the OLD blocking API must wake tickets waiting
    in the dispatcher's queue — both admission surfaces share one slot
    counter, so both must schedule (regression: the ticket used to wait
    until some unrelated dispatcher event happened to fire)."""
    mgr = _manager(ResourceGroupConfig("g", hard_concurrency=1, max_queued=4))
    d = QueryDispatcher(_DummyRunner(), mgr, lanes=2)
    g = mgr.groups["g"]
    g.acquire()  # legacy holder takes the only slot
    t = d.enqueue(group_name="g")  # dispatcher ticket queues behind it
    admitted = threading.Event()

    def waiter():
        t.wait()
        admitted.set()

    th = threading.Thread(target=waiter, daemon=True)
    th.start()
    time.sleep(0.05)
    assert not admitted.is_set()
    g.release()  # LEGACY release: must kick the dispatcher's scheduler
    assert admitted.wait(timeout=5), "legacy release never woke the ticket"
    d.release(t)
    th.join(timeout=5)


def test_lanes_share_transaction_state():
    """BEGIN on one lane, COMMIT on another: the HTTP protocol has no
    session affinity, so every lane must see ONE TransactionManager
    (the shared pre-dispatcher runner's semantics)."""
    from trino_tpu.runtime.runner import LocalQueryRunner

    primary = LocalQueryRunner()
    lane = primary.clone_for_dispatch()
    assert lane.transactions is primary.transactions
    lane.execute("start transaction")
    assert primary.in_transaction
    primary.execute("commit")
    assert not lane.in_transaction


def test_legacy_acquire_shares_the_concurrency_limit():
    """A slot held through the old blocking ResourceGroup.acquire() (dbapi
    sessions) counts against dispatcher admissions: one limit, two
    admission surfaces."""
    mgr = _manager(ResourceGroupConfig("g", hard_concurrency=1, max_queued=0))
    d = QueryDispatcher(_DummyRunner(), mgr, lanes=2)
    g = mgr.groups["g"]
    g.acquire()
    with pytest.raises(QueryShedError):
        d.enqueue(group_name="g")
    g.release()
    t = d.enqueue(group_name="g")
    assert t.wait() is not None
    d.release(t)


# -- system.prewarm admission --------------------------------------------------


def test_system_admission_holds_primary_lane_while_users_flow():
    d = QueryDispatcher(_DummyRunner(), _manager(), lanes=2)
    with d.system_admission() as runner:
        assert runner is d.runner  # primary lane granted
        stats = {s["name"]: s for s in d.stats()}
        assert stats["system.prewarm"]["running"] == 1
        # a user statement still admits on the second lane mid-replay
        t = d.enqueue()
        assert t.wait() is not None
        d.release(t)
    stats = {s["name"]: s for s in d.stats()}
    assert stats["system.prewarm"]["running"] == 0


# -- resource-group properties file --------------------------------------------


def test_resource_groups_from_properties():
    mgr = ResourceGroupManager.from_properties({
        "resource-groups.global.max-concurrency": "4",
        "resource-groups.etl.weight": "2",
        "resource-groups.etl.max-queued": "7",
        "resource-groups.etl.memory-limit-bytes": "1048576",
        "resource-groups.user.batch": "etl",
        "unrelated.key": "x",
    })
    assert mgr.default.config.hard_concurrency == 4
    etl = mgr.groups["etl"].config
    assert (etl.weight, etl.max_queued, etl.memory_limit_bytes) == (
        2, 7, 1048576
    )
    assert mgr.select("batch").config.name == "etl"
    assert mgr.select("adhoc").config.name == "global"
    with pytest.raises(ValueError):
        ResourceGroupManager.from_properties(
            {"resource-groups.g.max-concurency": "4"}  # typo must raise
        )
    with pytest.raises(ValueError):
        ResourceGroupManager.from_properties(
            {"resource-groups.user.u": "nope"}
        )


# -- per-group memory sub-pools ------------------------------------------------


def _pool_with_groups():
    from trino_tpu.runtime.memory import MemoryPool

    pool = MemoryPool(limit_bytes=0)
    ga = ResourceGroupConfig("a", memory_limit_bytes=1000)
    gb = ResourceGroupConfig("b", memory_limit_bytes=1000)
    from trino_tpu.runtime.resource_groups import ResourceGroup

    a = ResourceGroup(ga).memory_context(pool.root)
    b = ResourceGroup(gb).memory_context(pool.root)
    return pool, a, b


def _query_under(group_ctx, pool, name):
    q = group_ctx.child(name)
    q.is_query_root = True
    with pool.root._lock:
        group_ctx.query_children.append(q)
        pool.root.query_children.append(q)
    return q


class _Killable:
    def __init__(self):
        self.killed = None

    def kill(self, reason, detail=None):
        self.killed = (reason, detail)


def test_group_limit_kills_largest_in_group_never_bystander():
    pool, a, b = _pool_with_groups()
    q1 = _query_under(a, pool, "query:q1")
    q2 = _query_under(a, pool, "query:q2")
    q2.owner = _Killable()
    bystander = _query_under(b, pool, "query:by")
    bystander.owner = _Killable()
    bystander.add_bytes(900)  # group b, nearly at ITS limit
    q2.add_bytes(600)
    # q1's reservation breaches group a's 1000-byte limit; escalation
    # (installed by memory_context) kills q2 — the largest IN GROUP A —
    # and the reservation then fits
    q1.add_bytes(600)
    assert q2.owner.killed is not None and q2.owner.killed[0] == "memory"
    assert bystander.owner.killed is None  # never a cross-group kill
    assert bystander.reserved == 900
    assert a.reserved == 600 and q1.reserved == 600
    esc = a.on_exceeded
    assert esc.kill_log == [("a", "query:q2")]


def test_group_limit_requester_largest_fails_own_reservation():
    pool, a, _b = _pool_with_groups()
    q1 = _query_under(a, pool, "query:q1")
    q1.owner = _Killable()
    from trino_tpu.runtime.memory import ExceededMemoryLimitException

    q1.add_bytes(800)
    with pytest.raises(ExceededMemoryLimitException):
        q1.add_bytes(800)  # largest is the requester: no kill, raise
    assert q1.owner.killed is None
    assert q1.reserved == 800  # failed reservation fully rolled back


def test_group_revoke_tier_spills_own_group_only():
    from trino_tpu.runtime.spill import REVOCABLES, RevocableOperator

    pool, a, b = _pool_with_groups()
    qa = _query_under(a, pool, "query:qa")
    qb = _query_under(b, pool, "query:qb")
    qa_op = qa.child("join_build")
    qb_op = qb.child("join_build")
    qa_op.add_bytes(700)
    qb_op.add_bytes(900)
    freed = {"a": 0, "b": 0}

    def spill_a():
        freed["a"] += 1
        qa_op.set_bytes(0)
        return 700

    def spill_b():
        freed["b"] += 1
        qb_op.set_bytes(0)
        return 900

    ha = REVOCABLES.register(RevocableOperator("join", qa_op, spill_a))
    hb = REVOCABLES.register(RevocableOperator("join", qb_op, spill_b))
    try:
        # breach group a's limit: b's (larger) revocable must NOT be the
        # victim — only a's own operator spills
        qa.add_bytes(600)
        assert freed == {"a": 1, "b": 0}
        assert qa.reserved == 600
        assert qb_op.reserved == 900
    finally:
        ha.finish()
        hb.finish()


def test_sibling_group_pools_never_overadmit_root():
    """Satellite: N threads reserving against sibling group sub-pools can
    never push the shared root past its limit, even transiently at the
    accounting level (the check-and-reserve is atomic up the tree)."""
    from trino_tpu.runtime.memory import (
        ExceededMemoryLimitException,
        MemoryPool,
    )
    from trino_tpu.runtime.resource_groups import ResourceGroup

    pool = MemoryPool(limit_bytes=10_000)
    pool.root.on_exceeded = None
    groups = [
        ResourceGroup(
            ResourceGroupConfig(f"g{i}", memory_limit_bytes=8_000)
        ).memory_context(pool.root)
        for i in range(4)
    ]
    for g in groups:
        g.on_exceeded = None  # pure accounting: no escalation
    violations = []

    def hammer(g):
        q = _query_under(g, pool, "query:h")
        for _ in range(200):
            try:
                q.add_bytes(173)
            except ExceededMemoryLimitException:
                q.set_bytes(0)
            with pool.root._lock:
                if pool.root.reserved > pool.root.limit_bytes:
                    violations.append(pool.root.reserved)
        q.set_bytes(0)

    threads = [
        threading.Thread(target=hammer, args=(g,), daemon=True)
        for g in groups
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive()
    assert not violations
    assert pool.root.reserved == 0


def test_query_root_resolves_through_group_layer():
    pool, a, _b = _pool_with_groups()
    q = _query_under(a, pool, "query:q")
    op = q.child("aggregation")
    assert op.query_root() is q  # NOT the group node
    q.add_bytes(10)
    q.force_release()
    # deregistered from BOTH the group and the pool root
    assert q not in a.query_children
    assert q not in pool.root.query_children
    assert a.reserved == 0 and pool.root.reserved == 0


# -- coordinator integration ---------------------------------------------------


def test_coordinator_serves_concurrent_statements():
    from trino_tpu.server.coordinator import CoordinatorServer

    srv = CoordinatorServer(port=0)
    srv.start()
    try:
        assert srv.dispatcher.lanes >= 2  # LocalQueryRunner is cloneable
        qs = [
            srv.submit(f"select {i} + {i}") for i in range(4)
        ]
        for i, q in enumerate(qs):
            assert q.done.wait(timeout=30)
            assert q.state == "FINISHED", q.error
            assert q.result.rows == [(2 * i,)]
        # distinct engine query ids even across lanes (shared counter)
        hist = srv.runner.query_history.entries
        qids = [e["query_id"] for e in hist]
        assert len(qids) == len(set(qids))
    finally:
        srv.shutdown()


def test_coordinator_http_shed_429_with_retry_after():
    import urllib.request
    from urllib.error import HTTPError

    from trino_tpu.client import Client, QueryShed
    from trino_tpu.server.coordinator import CoordinatorServer

    rg = ResourceGroupManager(
        ResourceGroupConfig("global", hard_concurrency=1, max_queued=0)
    )
    srv = CoordinatorServer(port=0, resource_groups=rg)
    srv.start()
    try:
        rg.default.acquire()  # hold the only slot
        # raw HTTP: 429 + Retry-After, body never read
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/statement",
            data=b"select 1", method="POST",
        )
        with pytest.raises(HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 429
        assert int(ei.value.headers["Retry-After"]) >= 1
        # client surface: a typed retryable error
        with pytest.raises(QueryShed) as ci:
            Client(f"http://127.0.0.1:{srv.port}").execute("select 1")
        assert ci.value.retryable and ci.value.retry_after_s >= 1
        rg.default.release()
        # recovered: the same client round-trips
        names, rows = Client(
            f"http://127.0.0.1:{srv.port}"
        ).execute("select 1 as x")
        assert rows == [(1,)]
    finally:
        srv.shutdown()


def test_client_retries_race_window_shed():
    """The shed race window: shed_probe passes, the queue fills before the
    statement thread's enqueue, and the query fails through the POLL loop
    with a retryable QUERY_QUEUE_FULL object.  Client.execute(...,
    shed_retries=N) must retry that surface too, not just the 429."""
    from trino_tpu.client import Client, QueryShed
    from trino_tpu.server import protocol

    polled_error = protocol.query_results(
        "q_1",
        state="FAILED",
        error={
            "message": "shed in the race window",
            "errorName": "QUERY_QUEUE_FULL",
            "retryable": True,
            "retryAfterSeconds": 0.0,
        },
    )
    ok = protocol.query_results(
        "q_2", columns=[{"name": "x", "type": "bigint"}],
        data=protocol.encode_rows([(1,)]), state="FINISHED",
    )
    responses = [polled_error, ok]
    c = Client("http://unused")
    c._request = lambda method, path, body=None: responses.pop(0)
    names, rows = c.execute("select 1", shed_retries=1)
    assert rows == [(1,)]
    # without retries the typed shed error surfaces
    responses = [dict(polled_error)]
    with pytest.raises(QueryShed):
        c.execute("select 1")


def test_coordinator_queued_time_limit_classified():
    from trino_tpu.server.coordinator import CoordinatorServer

    rg = ResourceGroupManager(
        ResourceGroupConfig("global", hard_concurrency=1, max_queued=5)
    )
    srv = CoordinatorServer(port=0, resource_groups=rg)
    srv.runner.properties.set("query_max_queued_time", 0.05)
    srv.start()
    try:
        rg.default.acquire()
        q = srv.submit("select 1")
        assert q.done.wait(timeout=10)
        assert q.state == "FAILED"
        assert q.error["errorCode"] == "EXCEEDED_QUEUED_TIME_LIMIT"
        rg.default.release()
    finally:
        srv.shutdown()


def test_coordinator_cancel_while_queued_never_admits():
    from trino_tpu.server.coordinator import CoordinatorServer

    rg = ResourceGroupManager(
        ResourceGroupConfig("global", hard_concurrency=1, max_queued=5)
    )
    srv = CoordinatorServer(port=0, resource_groups=rg)
    srv.start()
    try:
        rg.default.acquire()
        before = {
            s["name"]: s["total_admitted"] for s in srv.dispatcher.stats()
        }
        q = srv.submit("select 1")
        time.sleep(0.05)  # let the statement thread enqueue
        q.cancel()
        assert q.done.wait(timeout=10)
        assert q.state == "CANCELED"
        assert q.error["errorCode"] == "USER_CANCELED"
        after = {
            s["name"]: s["total_admitted"] for s in srv.dispatcher.stats()
        }
        assert after == before  # never acquired an admission slot
        rg.default.release()
    finally:
        srv.shutdown()


def test_system_resource_groups_table():
    from trino_tpu.server.coordinator import CoordinatorServer

    srv = CoordinatorServer(port=0)
    srv.start()
    try:
        q = srv.submit(
            "select name, max_concurrency, weight from "
            "system.runtime.resource_groups order by name"
        )
        assert q.done.wait(timeout=30) and q.state == "FINISHED", q.error
        names = [r[0] for r in q.result.rows]
        assert "global" in names and "system.prewarm" in names
    finally:
        srv.shutdown()


def test_queued_span_recorded_in_trace():
    from trino_tpu.runtime import lifecycle
    from trino_tpu.runtime.runner import LocalQueryRunner

    r = LocalQueryRunner()
    token = lifecycle.set_admission_info(("global", 0.01))
    try:
        r.execute("select 1")
    finally:
        lifecycle.reset_admission_info(token)
    names = [e["name"] for e in r.last_trace["traceEvents"]]
    assert "queued" in names and "query" in names


# -- spill release on abort (satellite) ----------------------------------------


def test_mid_wave_kill_leaves_spill_dir_empty(tmp_path):
    """A query killed mid-wave releases its SpillManager partitions
    through the filesystem SPI at statement end — not at GC, not at the
    hours-scale orphan sweep."""
    from trino_tpu.config import install_config, load_cluster_config, reset_config
    from trino_tpu.runtime.lifecycle import QueryDeadlineExceeded
    from trino_tpu.runtime.runner import LocalQueryRunner
    from trino_tpu.telemetry.metrics import spill_bytes_counter

    spill_dir = tmp_path / "spill"
    spill_dir.mkdir()
    install_config(
        load_cluster_config({"memory.spill-dir": str(spill_dir)}, env={})
    )
    try:
        r = LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=2)
        r.properties.set("query_max_memory", 200_000)
        r.properties.set("memory_wave_partitions", 2)
        r.properties.set("query_max_run_time", 5.0)
        spill0 = spill_bytes_counter().value()

        def clock():
            # deadline blows exactly when the first partition hits disk:
            # deterministically "mid-wave", however fast the machine
            return 1000.0 if spill_bytes_counter().value() > spill0 else 0.0

        r.query_tracker.clock = clock
        with pytest.raises(QueryDeadlineExceeded):
            r.execute(
                "select o_orderpriority, count(*) from orders join "
                "lineitem on o_orderkey = l_orderkey group by "
                "o_orderpriority"
            )
        assert spill_bytes_counter().value() > spill0  # it DID spill
        leftovers = list(spill_dir.rglob("*.npz"))
        assert leftovers == [], f"leaked spill files: {leftovers}"
    finally:
        reset_config()


# -- fast serve-chaos (the CI step's core) -------------------------------------


def test_serve_chaos_fast():
    """K concurrent clients against one coordinator with small queues:
    every statement finishes with correct rows OR fails classified
    (shed | canceled | queued-time) — zero hangs, inside a short wall."""
    from trino_tpu.server.coordinator import CoordinatorServer

    rg = ResourceGroupManager(
        ResourceGroupConfig("global", hard_concurrency=2, max_queued=4)
    )
    srv = CoordinatorServer(port=0, resource_groups=rg)
    srv.start()
    oracle = {
        "select count(*) from tpch.tiny.region": (5,),
        "select count(*) from tpch.tiny.nation": (25,),
        "select 40 + 2": (42,),
    }
    allowed = {
        "QUERY_QUEUE_FULL", "USER_CANCELED", "EXCEEDED_QUEUED_TIME_LIMIT",
        "SERVER_SHUTTING_DOWN",
    }
    outcomes = []
    lock = threading.Lock()

    def client(i):
        sqls = list(oracle)
        for j in range(3):
            sql = sqls[(i + j) % len(sqls)]
            q = srv.submit(sql)
            if (i + j) % 7 == 3:
                q.cancel()  # cancel storms ride along
            assert q.done.wait(timeout=60), "hang"
            with lock:
                if q.state == "FINISHED":
                    assert q.result.rows == [oracle[sql]]
                    outcomes.append("ok")
                else:
                    code = (q.error or {}).get("errorCode") or (
                        q.error or {}
                    ).get("errorName")
                    assert code in allowed, q.error
                    outcomes.append(code)

    try:
        threads = [
            threading.Thread(target=client, args=(i,), daemon=True)
            for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "serve chaos hung"
        assert outcomes.count("ok") >= 1  # progress under churn
    finally:
        srv.shutdown()


def test_concurrent_lanes_isolate_decision_ledgers():
    """Dispatcher lanes serve statements concurrently: every archived
    profile carries ITS OWN statement's finalized decision ledger (the
    lifecycle-contextvar resolution — never a shared runner attribute a
    neighboring lane could overwrite)."""
    from trino_tpu.runtime.runner import LocalQueryRunner
    from trino_tpu.server.coordinator import CoordinatorServer
    from trino_tpu.telemetry.profile_store import (
        ProfileStore,
        attach_profile_store,
    )

    r = LocalQueryRunner()
    store = ProfileStore()
    attach_profile_store(r, store)
    srv = CoordinatorServer(runner=r, port=0)
    srv.start()
    try:
        assert srv.dispatcher.lanes >= 2
        qs = [srv.submit(f"select {i} + {i}") for i in range(6)]
        for i, q in enumerate(qs):
            assert q.done.wait(timeout=30)
            assert q.state == "FINISHED", q.error
        arts = [store.get(ref["key"]) for ref in store.refs()]
        assert len(arts) == 6
        for a in arts:
            led = a["decisions"]
            assert led is not None and led["finalized"] is True
            assert led["query_id"] == a["query_id"]
            assert led["unattributed_bytes_by"] == {}
        # six statements, six distinct ledgers — ids never collide even
        # when lanes interleave
        qids = [a["decisions"]["query_id"] for a in arts]
        assert len(qids) == len(set(qids))
    finally:
        srv.shutdown()
