"""Fault-tolerant execution: stage retry with spooled outputs + heartbeats.

Reference style: BaseFailureRecoveryTest (testing/trino-testing/.../
BaseFailureRecoveryTest.java:78) — inject failures at chosen stages and
assert queries still succeed under retry_policy=TASK, without re-running
finished stages."""

import pytest


from trino_tpu.parallel import DistributedQueryRunner
from trino_tpu.runtime.retry import FAILURE_INJECTOR, InjectedFailure
from trino_tpu.runtime.runner import LocalQueryRunner

pytestmark = pytest.mark.heavy


@pytest.fixture(autouse=True)
def clean_injector():
    FAILURE_INJECTOR.clear()
    yield
    FAILURE_INJECTOR.clear()


@pytest.fixture(autouse=True)
def no_spool_leaks():
    """Every query-owned spool directory must be gone when the query ends
    (SpoolManager.close): chaos tests that leak orphan .npz spools fail
    HERE, not as unbounded /tmp growth in a long-lived deployment."""
    import glob
    import os
    import tempfile

    pat = os.path.join(tempfile.gettempdir(), "trino_tpu_spool_*")
    before = set(glob.glob(pat))
    yield
    leaked = set(glob.glob(pat)) - before
    assert not leaked, f"spool directories leaked: {sorted(leaked)}"


SQL = (
    "select n_regionkey, count(*) c, sum(n_nationkey) s from nation "
    "group by n_regionkey"
)


def _task_runner():
    r = DistributedQueryRunner(n_workers=8)
    r.properties.set("retry_policy", "TASK")
    return r


def test_stage_failure_retried_without_full_rerun():
    """A stage killed mid-query (after its children finished) re-executes
    alone; finished stages are served from memo/spool and never re-run."""
    r = _task_runner()
    expected = sorted(LocalQueryRunner().execute(SQL).rows)
    # fail the FINAL stage once, after its body ran
    FAILURE_INJECTOR.inject("stage:2:finish", times=1)
    res = r.execute(SQL)
    assert sorted(res.rows) == expected
    # the scan stage (fragment 0) started exactly once
    starts = {
        k: v for k, v in FAILURE_INJECTOR.visits.items()
        if k.startswith("stage:") and not k.endswith(":finish")
    }
    assert starts.get("stage:0") == 1, starts
    assert starts.get("stage:2") == 2, starts  # failed once, retried once


def test_stage_failure_at_start_retried():
    r = _task_runner()
    FAILURE_INJECTOR.inject("stage:1", times=2)
    res = r.execute(SQL)
    assert res.row_count == 5


def test_retry_budget_exhausted_fails():
    from trino_tpu.runtime.retry import StageFailedException

    r = _task_runner()
    FAILURE_INJECTOR.inject("stage:0", times=99)
    with pytest.raises(StageFailedException):
        r.execute(SQL)
    # the budget is per-stage, not multiplicative across consumers
    assert FAILURE_INJECTOR.visits.get("stage:0", 0) == 4


def test_spool_roundtrip_serves_stage_output(tmp_path):
    """Spooled fragment outputs rehydrate exactly (ExchangeManager role)."""
    import numpy as np

    from trino_tpu import types as T
    from trino_tpu.columnar import Batch, Column
    from trino_tpu.planner.plan import Symbol
    from trino_tpu.runtime.fte import SpoolManager

    sp = SpoolManager(str(tmp_path))
    cols = [
        Column(np.arange(8, dtype=np.int64), T.BIGINT, None),
        Column(
            np.linspace(0, 1, 8), T.DOUBLE, np.arange(8) % 2 == 0
        ),
    ]
    b = Batch(cols, np.arange(8) < 5)
    sp.save("q1", 3, [b], None)
    syms = [Symbol("a", T.BIGINT), Symbol("b", T.DOUBLE)]
    out = sp.load("q1", 3, syms, [None, None])
    assert len(out) == 1
    assert out[0].to_pylist() == b.to_pylist()


def test_heartbeat_detector():
    from trino_tpu.runtime.fte import HeartbeatFailureDetector

    now = [0.0]
    det = HeartbeatFailureDetector(timeout_s=5.0, clock=lambda: now[0])
    det.register("w0")
    det.register("w1")
    assert det.failed_workers() == set()
    now[0] = 3.0
    det.heartbeat("w1")
    now[0] = 6.0  # w0 last seen at 0 -> stale; w1 at 3 -> alive
    assert det.failed_workers() == {"w0"}
    assert det.active_workers() == ["w1"]
    det.heartbeat("w0")  # recovery clears the failure mark
    assert det.failed_workers() == set()


def test_dead_worker_blocks_query():
    """In-process mesh workers are always alive; a stale REMOTE registration
    (server-mode worker) blocks scheduling."""
    r = _task_runner()
    r.failure_detector.register("remote-worker-9")
    # age the registration far past the timeout (the detector is a facade
    # over the membership registry — last_heartbeat lives on its entry)
    r.failure_detector.membership._workers[
        "remote-worker-9"
    ].last_heartbeat = -1e9
    with pytest.raises(RuntimeError, match="heartbeat"):
        r.execute(SQL)
    # recovery: the remote worker heartbeats again and queries proceed
    r.failure_detector.heartbeat("remote-worker-9")
    assert r.execute(SQL).row_count == 5

def test_spool_rides_filesystem_spi(tmp_path):
    """The spool resolves its storage through the filesystem SPI; remote
    schemes fail loudly at configuration time."""
    import pytest as _pt

    from trino_tpu.runtime.fte import SpoolManager

    s = SpoolManager(str(tmp_path / "spool"))
    import numpy as np

    from trino_tpu.columnar import Batch, Column
    from trino_tpu import types as T

    b = Batch([Column(np.arange(4), T.BIGINT)], np.ones(4, bool))
    from trino_tpu.planner.plan import Symbol

    syms = [Symbol("x", T.BIGINT)]
    s.save("q1", 0, [b], syms)
    assert s.exists("q1", 0)
    out = s.load("q1", 0, syms, [None])
    assert np.array_equal(np.asarray(out[0].columns[0].data), np.arange(4))

    with _pt.raises(NotImplementedError, match="s3"):
        SpoolManager("s3://bucket/spool")


def _one_batch(n: int = 4):
    import numpy as np

    from trino_tpu import types as T
    from trino_tpu.columnar import Batch, Column
    from trino_tpu.planner.plan import Symbol

    b = Batch(
        [Column(np.arange(n, dtype=np.int64), T.BIGINT)], np.ones(n, bool)
    )
    return b, [Symbol("x", T.BIGINT)]


def test_crash_atomic_save_leaves_no_torn_npz(tmp_path, monkeypatch):
    """A writer killed mid-save must leave NOTHING a retrying consumer
    could load: the partial bytes live in a .tmp sibling that is deleted
    on the way out, and the committed .npz name never appears."""
    import os

    from trino_tpu.runtime import fte as fmod

    sp = fmod.SpoolManager(str(tmp_path / "spool"))
    b, syms = _one_batch()

    class Killed(RuntimeError):
        pass

    real_savez = fmod.np.savez

    def torn_savez(f, **arrays):
        f.write(b"\x93NUMPY-torn")  # partial bytes, then the "crash"
        raise Killed("writer killed mid-save")

    monkeypatch.setattr(fmod.np, "savez", torn_savez)
    with pytest.raises(Killed):
        sp.save("q1", 0, [b], syms)
    # no committed file, no torn sibling, nothing to load
    assert not sp.exists("q1", 0)
    assert os.listdir(sp.dir) == []
    assert sp.load("q1", 0, syms, [None]) is None
    # the next (surviving) writer succeeds on the same key
    monkeypatch.setattr(fmod.np, "savez", real_savez)
    sp.save("q1", 0, [b], syms)
    out = sp.load("q1", 0, syms, [None])
    assert out[0].to_pylist() == b.to_pylist()


def test_duplicate_attempts_dedup_and_discard(tmp_path):
    """Speculative/duplicate attempt outputs for one (query, fragment):
    the first COMMITTED attempt wins for every consumer, a later commit is
    a no-op, and the losing attempts are deleted unread."""
    import numpy as np

    from trino_tpu import types as T
    from trino_tpu.columnar import Batch, Column
    from trino_tpu.planner.plan import Symbol
    from trino_tpu.runtime.fte import SpoolManager

    sp = SpoolManager(str(tmp_path / "spool"))
    syms = [Symbol("x", T.BIGINT)]
    b0 = Batch([Column(np.arange(4), T.BIGINT)], np.ones(4, bool))
    b1 = Batch([Column(np.arange(4) + 100, T.BIGINT)], np.ones(4, bool))
    sp.save("q1", 2, [b0], syms, attempt_id=0)
    sp.save("q1", 2, [b1], syms, attempt_id=1)
    assert sp.attempts("q1", 2) == [0, 1]
    assert sp.dedup.commit("q1", 2, 0) == 0
    # a duplicate attempt's commit is told which attempt won
    assert sp.dedup.commit("q1", 2, 1) == 0
    assert sp.dedup.committed("q1", 2) == 0
    assert sp.discard_duplicates("q1", 2, 0) == 1
    assert sp.attempts("q1", 2) == [0]
    out = sp.load("q1", 2, syms, [None], attempt_id=0)
    assert out[0].to_pylist() == b0.to_pylist()


def test_recovery_classification_table():
    """Per-error-code recovery classification: worker death/drain and
    transient fetch RETRY (same plan, lost tasks only); a mesh truly
    shrunk below the plan's requirement RE-PLANS; user/semantic errors
    FAIL and are never retried."""
    from trino_tpu.runtime.lifecycle import (
        FAIL,
        RECOVERY_CLASSIFICATION,
        REPLAN,
        RETRY,
        error_code_of,
        recovery_action,
    )
    from trino_tpu.runtime.membership import (
        MeshChangedError,
        WorkerDrainingError,
    )
    from trino_tpu.runtime.retry import StageFailedException

    dead = MeshChangedError(dead=("w1",))
    assert error_code_of(dead) == "WORKER_DEATH"
    assert recovery_action(dead) == RETRY
    drained = MeshChangedError(drained=("w2",))
    assert error_code_of(drained) == "WORKER_DRAIN"
    assert recovery_action(drained) == RETRY
    # WorkerDrainingError subclasses ConnectionRefusedError; it must
    # classify as the drain, not the generic transient fetch
    assert error_code_of(WorkerDrainingError("503")) == "WORKER_DRAIN"
    assert recovery_action(ConnectionError("reset")) == RETRY
    assert recovery_action(TimeoutError("fetch")) == RETRY
    assert RECOVERY_CLASSIFICATION["MESH_SHRINK_BELOW_REQUIREMENT"] == REPLAN
    # stage budget exhaustion and unknown errors are terminal
    assert recovery_action(StageFailedException("stage 0 failed")) == FAIL
    assert recovery_action(ValueError("semantic")) == FAIL


def test_fte_property_enables_task_retry():
    """fault_tolerant_execution=true turns on the whole TASK machinery
    (spooled outputs + per-stage retry) without touching retry_policy;
    finished stages are never re-run."""
    r = DistributedQueryRunner(n_workers=8)
    assert r.properties.get("retry_policy") == "NONE"
    r.properties.set("fault_tolerant_execution", True)
    expected = sorted(LocalQueryRunner().execute(SQL).rows)
    FAILURE_INJECTOR.inject("stage:2:finish", times=1)
    res = r.execute(SQL)
    assert sorted(res.rows) == expected
    starts = {
        k: v for k, v in FAILURE_INJECTOR.visits.items()
        if k.startswith("stage:") and not k.endswith(":finish")
    }
    assert starts.get("stage:0") == 1, starts
    assert starts.get("stage:2") == 2, starts


def test_duplicate_attempt_spool_consumer_dedup():
    """A stage killed AFTER its output durably spooled retries and spools
    a SECOND attempt for the same fragment — the consumer commits exactly
    one and the query answers exactly once (DeduplicatingDirectExchange-
    Buffer role)."""
    from trino_tpu.telemetry.metrics import task_retries_counter

    r = _task_runner()
    expected = sorted(LocalQueryRunner().execute(SQL).rows)
    retries_before = task_retries_counter().labels("retry").value()
    # fires after attempt 0's spool save: the retry's spool is a duplicate
    FAILURE_INJECTOR.inject("stage:0:spooled", times=1)
    res = r.execute(SQL)
    assert sorted(res.rows) == expected
    assert FAILURE_INJECTOR.visits.get("stage:0") == 2
    assert (
        task_retries_counter().labels("retry").value() == retries_before + 1
    )


def test_spooled_dictionary_refs_rehydrate_after_restart(tmp_path):
    """Satellite: a spooled fragment whose varchar column ships dictionary
    CODES round-trips a coordinator restart — the (key, version) ref
    resolves through the dictionary service snapshot, and a mismatched
    dictionary raises instead of silently mis-decoding."""
    import numpy as np

    from trino_tpu import types as T
    from trino_tpu.columnar import Batch, Column
    from trino_tpu.columnar.dictionary import StringDictionary
    from trino_tpu.planner.plan import Symbol
    from trino_tpu.runtime.dictionary_service import GlobalDictionaryService
    from trino_tpu.runtime.fte import SpoolManager

    svc = GlobalDictionaryService()
    d = StringDictionary(["APAC", "EMEA", "LATAM"])
    key, version = svc.register("tpch", "tiny", "region", "r_name", d).ref
    syms = [Symbol("r", T.VARCHAR)]
    codes = np.array([0, 2, 1, 2], dtype=np.int64)
    b = Batch([Column(codes, T.VARCHAR, None, d)], np.ones(4, bool))
    # the spool persists CODES + the (key, version) ref's dictionary
    sp = SpoolManager(str(tmp_path / "spool"))
    sp.save("q7", 1, [b], syms)
    assert svc.ref_of(d) == (key, version)

    # coordinator restart: snapshot -> fresh process state -> load
    snap = str(tmp_path / "dictionaries.json")
    svc.save_snapshot(snap)
    svc.reset()
    assert svc.ref_of(d) is None  # registry is empty post-restart
    assert svc.load_snapshot(snap) >= 1
    d2 = svc.resolve(key, version)
    assert tuple(d2.values) == ("APAC", "EMEA", "LATAM")

    # a NEW spool manager over the same directory (the restarted
    # coordinator) decodes the spooled codes through the resolved ref
    out = SpoolManager(str(tmp_path / "spool")).load("q7", 1, syms, [d2])
    assert out[0].to_pylist() == b.to_pylist()

    # never silently wrong: a dictionary too small for the stored codes
    # fails the load validation loudly
    wrong = StringDictionary(["A", "B"])
    with pytest.raises(ValueError, match="dictionary"):
        SpoolManager(str(tmp_path / "spool")).load("q7", 1, syms, [wrong])


def test_remote_fte_resumes_from_spooled_fragments():
    """Multi-host tentpole e2e: a worker killed mid-query under
    fault_tolerant_execution RETRIES the same plan on the survivors —
    the already-fetched fragment resumes from its spooled output
    (spool hit), only the lost fragment re-runs, and the query is NEVER
    re-planned."""
    from trino_tpu.parallel import remote as rmod
    from trino_tpu.parallel.remote import MultiHostQueryRunner
    from trino_tpu.server.worker import WorkerServer

    ws = [WorkerServer(port=0).start() for _ in range(3)]
    victim = ws[1]
    try:
        mh = MultiHostQueryRunner(
            [w.url for w in ws], catalog="tpch", schema="tiny"
        )
        mh.properties.set("fault_tolerant_execution", True)
        # two coordinator-consumed gather fragments: frag 0 (nation) is
        # fully fetched + spooled before frag 1 (region) starts
        q = (
            "select count(*) from nation "
            "union all select count(*) from region"
        )
        expected = LocalQueryRunner(catalog="tpch", schema="tiny").execute(
            q
        ).rows
        orig_fetch = rmod._fetch_ok
        state = {"calls": 0}

        def killing_fetch(task, *a, **kw):
            state["calls"] += 1
            # frag 0's three producers are calls 1-3; kill the victim as
            # frag 1's first result is pulled, so its loss cannot touch
            # the finished (spooled) fragment
            if state["calls"] == 4:
                victim.shutdown()
            return orig_fetch(task, *a, **kw)

        rmod._fetch_ok = killing_fetch
        try:
            got = mh.execute(q).rows
        finally:
            rmod._fetch_ok = orig_fetch
        assert sorted(got) == sorted(expected)
        assert mh.last_task_retries >= 1  # classified retry, not replan
        assert mh.last_spool_hits >= 1  # frag 0 resumed from the spool
        assert mh.last_replans == 0  # finished work never re-planned
    finally:
        for w in ws:
            try:
                w.shutdown()
            except Exception:
                pass
