"""Fault-tolerant execution: stage retry with spooled outputs + heartbeats.

Reference style: BaseFailureRecoveryTest (testing/trino-testing/.../
BaseFailureRecoveryTest.java:78) — inject failures at chosen stages and
assert queries still succeed under retry_policy=TASK, without re-running
finished stages."""

import pytest


from trino_tpu.parallel import DistributedQueryRunner
from trino_tpu.runtime.retry import FAILURE_INJECTOR, InjectedFailure
from trino_tpu.runtime.runner import LocalQueryRunner

pytestmark = pytest.mark.heavy


@pytest.fixture(autouse=True)
def clean_injector():
    FAILURE_INJECTOR.clear()
    yield
    FAILURE_INJECTOR.clear()


SQL = (
    "select n_regionkey, count(*) c, sum(n_nationkey) s from nation "
    "group by n_regionkey"
)


def _task_runner():
    r = DistributedQueryRunner(n_workers=8)
    r.properties.set("retry_policy", "TASK")
    return r


def test_stage_failure_retried_without_full_rerun():
    """A stage killed mid-query (after its children finished) re-executes
    alone; finished stages are served from memo/spool and never re-run."""
    r = _task_runner()
    expected = sorted(LocalQueryRunner().execute(SQL).rows)
    # fail the FINAL stage once, after its body ran
    FAILURE_INJECTOR.inject("stage:2:finish", times=1)
    res = r.execute(SQL)
    assert sorted(res.rows) == expected
    # the scan stage (fragment 0) started exactly once
    starts = {
        k: v for k, v in FAILURE_INJECTOR.visits.items()
        if k.startswith("stage:") and not k.endswith(":finish")
    }
    assert starts.get("stage:0") == 1, starts
    assert starts.get("stage:2") == 2, starts  # failed once, retried once


def test_stage_failure_at_start_retried():
    r = _task_runner()
    FAILURE_INJECTOR.inject("stage:1", times=2)
    res = r.execute(SQL)
    assert res.row_count == 5


def test_retry_budget_exhausted_fails():
    from trino_tpu.runtime.retry import StageFailedException

    r = _task_runner()
    FAILURE_INJECTOR.inject("stage:0", times=99)
    with pytest.raises(StageFailedException):
        r.execute(SQL)
    # the budget is per-stage, not multiplicative across consumers
    assert FAILURE_INJECTOR.visits.get("stage:0", 0) == 4


def test_spool_roundtrip_serves_stage_output(tmp_path):
    """Spooled fragment outputs rehydrate exactly (ExchangeManager role)."""
    import numpy as np

    from trino_tpu import types as T
    from trino_tpu.columnar import Batch, Column
    from trino_tpu.planner.plan import Symbol
    from trino_tpu.runtime.fte import SpoolManager

    sp = SpoolManager(str(tmp_path))
    cols = [
        Column(np.arange(8, dtype=np.int64), T.BIGINT, None),
        Column(
            np.linspace(0, 1, 8), T.DOUBLE, np.arange(8) % 2 == 0
        ),
    ]
    b = Batch(cols, np.arange(8) < 5)
    sp.save("q1", 3, [b], None)
    syms = [Symbol("a", T.BIGINT), Symbol("b", T.DOUBLE)]
    out = sp.load("q1", 3, syms, [None, None])
    assert len(out) == 1
    assert out[0].to_pylist() == b.to_pylist()


def test_heartbeat_detector():
    from trino_tpu.runtime.fte import HeartbeatFailureDetector

    now = [0.0]
    det = HeartbeatFailureDetector(timeout_s=5.0, clock=lambda: now[0])
    det.register("w0")
    det.register("w1")
    assert det.failed_workers() == set()
    now[0] = 3.0
    det.heartbeat("w1")
    now[0] = 6.0  # w0 last seen at 0 -> stale; w1 at 3 -> alive
    assert det.failed_workers() == {"w0"}
    assert det.active_workers() == ["w1"]
    det.heartbeat("w0")  # recovery clears the failure mark
    assert det.failed_workers() == set()


def test_dead_worker_blocks_query():
    """In-process mesh workers are always alive; a stale REMOTE registration
    (server-mode worker) blocks scheduling."""
    r = _task_runner()
    r.failure_detector.register("remote-worker-9")
    r.failure_detector._last["remote-worker-9"] = -1e9
    with pytest.raises(RuntimeError, match="heartbeat"):
        r.execute(SQL)
    # recovery: the remote worker heartbeats again and queries proceed
    r.failure_detector.heartbeat("remote-worker-9")
    assert r.execute(SQL).row_count == 5

def test_spool_rides_filesystem_spi(tmp_path):
    """The spool resolves its storage through the filesystem SPI; remote
    schemes fail loudly at configuration time."""
    import pytest as _pt

    from trino_tpu.runtime.fte import SpoolManager

    s = SpoolManager(str(tmp_path / "spool"))
    import numpy as np

    from trino_tpu.columnar import Batch, Column
    from trino_tpu import types as T

    b = Batch([Column(np.arange(4), T.BIGINT)], np.ones(4, bool))
    from trino_tpu.planner.plan import Symbol

    syms = [Symbol("x", T.BIGINT)]
    s.save("q1", 0, [b], syms)
    assert s.exists("q1", 0)
    out = s.load("q1", 0, syms, [None])
    assert np.array_equal(np.asarray(out[0].columns[0].data), np.arange(4))

    with _pt.raises(NotImplementedError, match="s3"):
        SpoolManager("s3://bucket/spool")
