"""approx_distinct (HyperLogLog) + aggregation memory waves.

Reference roles: operator/aggregation/ApproximateCountDistinctAggregation
.java + state/HyperLogLogStateFactory.java:23 (mergeable bounded sketch
state), HashAggregationOperator.startMemoryRevoke:449 (memory-bounded
grouped aggregation).
"""

import pytest

pytestmark = pytest.mark.smoke

from trino_tpu.runtime.runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=4)


def _exact(runner, col, table):
    return runner.execute(f"select count(distinct {col}) from {table}").rows[0][0]


@pytest.mark.parametrize(
    "col,table",
    [
        ("l_orderkey", "lineitem"),   # ~15k distinct at tiny
        ("l_partkey", "lineitem"),    # ~2k
        ("l_shipdate", "lineitem"),   # ~2.5k distinct dates
        ("l_returnflag", "lineitem"), # 3 distinct strings (dictionary hash)
        ("l_discount", "lineitem"),   # 11 distinct decimals
    ],
)
def test_approx_distinct_within_error(runner, col, table):
    exact = _exact(runner, col, table)
    got = runner.execute(f"select approx_distinct({col}) from {table}").rows[0][0]
    # p=13 registers: standard error ~1.15%; assert 3 sigma + small-N slack
    assert abs(got - exact) <= max(3, 0.04 * exact), (got, exact)


def test_approx_distinct_null_and_empty(runner):
    # empty input and all-NULL input both count 0 (count-like semantics)
    assert runner.execute(
        "select approx_distinct(l_orderkey) from lineitem where l_orderkey < 0"
    ).rows == [(0,)]


def test_approx_distinct_merges_across_batches(runner):
    # target_splits=4 feeds multiple batches: per-batch register states must
    # merge by elementwise max into the same estimate a single batch gives
    one = LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=1)
    q = "select approx_distinct(l_suppkey) from lineitem"
    assert runner.execute(q).rows == one.execute(q).rows


def test_grouped_approx_distinct_falls_back_exact(runner):
    got = runner.execute(
        "select l_returnflag, approx_distinct(l_linenumber) from lineitem "
        "group by l_returnflag order by l_returnflag"
    ).rows
    want = runner.execute(
        "select l_returnflag, count(distinct l_linenumber) from lineitem "
        "group by l_returnflag order by l_returnflag"
    ).rows
    assert got == want


def test_distributed_approx_distinct_matches_local():
    from trino_tpu.parallel import DistributedQueryRunner

    d = DistributedQueryRunner(catalog="tpch", schema="tiny", n_workers=4)
    l = LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=4)
    q = "select approx_distinct(o_custkey) from orders"
    # the sketch is deterministic and merge is exact max: same registers,
    # same estimate, regardless of how rows were partitioned
    assert d.execute(q).rows == l.execute(q).rows


def test_agg_waves_exact_under_budget():
    r = LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=4)
    q = (
        "select l_orderkey, sum(l_quantity) q, count(*) c from lineitem "
        "group by l_orderkey order by q desc, l_orderkey limit 5"
    )
    base = r.execute(q).rows
    r.execute("set session query_max_memory_bytes = 200000")
    waved = r.execute(q).rows
    assert base == waved


def test_agg_waves_with_having_and_avg():
    r = LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=4)
    q = (
        "select o_custkey, avg(o_totalprice) a from orders "
        "group by o_custkey having count(*) > 2 order by a desc limit 3"
    )
    base = r.execute(q).rows
    r.execute("set session query_max_memory_bytes = 150000")
    waved = r.execute(q).rows
    assert base == waved
