"""Shared connector conformance suite.

Reference role: testing/trino-testing's BaseConnectorTest — ONE battery of
behavioral checks every connector must pass, parameterized over the
connectors instead of copy-pasted per plugin.  Writable connectors run the
full DML battery; generator-backed connectors run the read battery.
"""

import datetime

import pytest

pytestmark = pytest.mark.smoke

WRITABLE = ["memory", "iceberg"]
READ_ONLY = [("tpch", "tiny", "nation", 25), ("tpcds", "tiny", "reason", 35)]


@pytest.fixture()
def runner(request, tmp_path):
    """LocalQueryRunner with every conformance-tested catalog mounted."""
    from trino_tpu.connectors.api import default_catalogs
    from trino_tpu.connectors.iceberg import IcebergConnector
    from trino_tpu.runtime.runner import LocalQueryRunner

    cm = default_catalogs()
    cm.register("iceberg", IcebergConnector(str(tmp_path / "warehouse")))
    return LocalQueryRunner(
        catalogs=cm, catalog="memory", schema="default", target_splits=2
    )


def _t(catalog):
    return f"{catalog}.default.conf_t"


@pytest.mark.parametrize("catalog", WRITABLE)
class TestWritableConnector:
    """The write-path battery (BaseConnectorTest testCreateTable /
    testInsert / testDelete / testUpdate analogs)."""

    def test_create_insert_select(self, runner, catalog):
        runner.execute(
            f"create table {_t(catalog)} (k bigint, s varchar, d double)"
        )
        runner.execute(
            f"insert into {_t(catalog)} values "
            "(1, 'a', 1.5), (2, 'b', 2.5), (3, null, null)"
        )
        rows = sorted(runner.execute(f"select * from {_t(catalog)}").rows)
        assert rows == [(1, "a", 1.5), (2, "b", 2.5), (3, None, None)]

    def test_predicate_and_agg(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (k bigint, v double)")
        runner.execute(
            f"insert into {_t(catalog)} values (1, 10.0), (1, 20.0), (2, 5.0)"
        )
        assert runner.execute(
            f"select k, sum(v) from {_t(catalog)} where v > 6 "
            "group by k order by k"
        ).rows == [(1, 30.0)]

    def test_join_with_fixture(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (rk bigint)")
        runner.execute(f"insert into {_t(catalog)} values (0), (2)")
        rows = runner.execute(
            f"select r.r_name from {_t(catalog)} t "
            "join tpch.tiny.region r on t.rk = r.r_regionkey order by 1"
        ).rows
        assert rows == [("AFRICA",), ("ASIA",)]

    def test_delete_update(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (k bigint, v bigint)")
        runner.execute(
            f"insert into {_t(catalog)} values (1, 10), (2, 20), (3, 30)"
        )
        runner.execute(f"delete from {_t(catalog)} where k = 2")
        runner.execute(f"update {_t(catalog)} set v = v + 1 where k = 3")
        assert sorted(runner.execute(f"select * from {_t(catalog)}").rows) == [
            (1, 10), (3, 31),
        ]

    def test_types_roundtrip(self, runner, catalog):
        runner.execute(
            f"create table {_t(catalog)} "
            "(b boolean, i integer, x bigint, r double, "
            "dec decimal(10,2), dt date, s varchar)"
        )
        runner.execute(
            f"insert into {_t(catalog)} values "
            "(true, 7, 9000000000, 1.25, 3.50, date '2020-02-29', 'z')"
        )
        row = runner.execute(f"select * from {_t(catalog)}").rows[0]
        assert row[0] is True and row[1] == 7 and row[2] == 9000000000
        assert row[3] == 1.25 and float(row[4]) == 3.5
        assert row[5] == datetime.date(2020, 2, 29) and row[6] == "z"

    def test_show_columns_and_drop(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (k bigint, s varchar)")
        cols = runner.execute(f"show columns from {_t(catalog)}").rows
        assert [c[0] for c in cols] == ["k", "s"]
        runner.execute(f"drop table {_t(catalog)}")
        tables = runner.execute(f"show tables from {catalog}.default").rows
        assert ("conf_t",) not in tables

    def test_insert_column_subset(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (a bigint, b varchar)")
        runner.execute(f"insert into {_t(catalog)} (b) values ('only-b')")
        assert runner.execute(f"select * from {_t(catalog)}").rows == [
            (None, "only-b")
        ]


@pytest.mark.parametrize("catalog,schema,table,expected", READ_ONLY)
class TestReadOnlyConnector:
    """Generator/fixture connector battery (AbstractTestQueries-style)."""

    def test_count(self, runner, catalog, schema, table, expected):
        assert runner.execute(
            f"select count(*) from {catalog}.{schema}.{table}"
        ).rows == [(expected,)]

    def test_predicate_scan(self, runner, catalog, schema, table, expected):
        total = runner.execute(
            f"select count(*) from {catalog}.{schema}.{table}"
        ).only_value()
        pk = runner.execute(
            f"show columns from {catalog}.{schema}.{table}"
        ).rows[0][0]
        some = runner.execute(
            f"select count(*) from {catalog}.{schema}.{table} where {pk} >= 1"
        ).only_value()
        assert 0 < some <= total

    def test_stats_present(self, runner, catalog, schema, table, expected):
        rows = runner.execute(
            f"show stats for {catalog}.{schema}.{table}"
        ).rows
        summary = [r for r in rows if r[0] is None]
        assert summary and summary[0][4] == float(expected)

    def test_writes_rejected(self, runner, catalog, schema, table, expected):
        with pytest.raises(Exception):
            runner.execute(
                f"insert into {catalog}.{schema}.{table} values (1)"
            )


@pytest.mark.parametrize("catalog", WRITABLE)
class TestWritableConnectorExtended:
    """Round-5 widening (BaseConnectorTest breadth: NULL handling, schema
    evolution, CTAS, views over connector tables, transactional rollback)."""

    def test_insert_all_nulls_row(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (k bigint, s varchar)")
        runner.execute(f"insert into {_t(catalog)} values (null, null)")
        assert runner.execute(f"select * from {_t(catalog)}").rows == [
            (None, None)
        ]
        assert runner.execute(
            f"select count(*), count(k) from {_t(catalog)}"
        ).rows == [(1, 0)]

    def test_empty_table_aggregates(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (k bigint)")
        assert runner.execute(
            f"select count(*), sum(k), min(k) from {_t(catalog)}"
        ).rows == [(0, None, None)]

    def test_ctas_roundtrip(self, runner, catalog):
        runner.execute(
            f"create table {_t(catalog)} as "
            "select n_nationkey k, n_name s from tpch.tiny.nation "
            "where n_nationkey < 3"
        )
        assert runner.execute(
            f"select count(*) from {_t(catalog)}"
        ).rows == [(3,)]

    def test_add_column_schema_evolution(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (k bigint)")
        runner.execute(f"insert into {_t(catalog)} values (1)")
        runner.execute(f"alter table {_t(catalog)} add column s varchar")
        runner.execute(f"insert into {_t(catalog)} values (2, 'x')")
        assert sorted(
            runner.execute(f"select * from {_t(catalog)}").rows
        ) == [(1, None), (2, "x")]

    def test_rename_column(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (old bigint)")
        runner.execute(f"insert into {_t(catalog)} values (5)")
        runner.execute(
            f"alter table {_t(catalog)} rename column old to renamed"
        )
        assert runner.execute(
            f"select renamed from {_t(catalog)}"
        ).rows == [(5,)]

    def test_drop_column(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (a bigint, b bigint)")
        runner.execute(f"insert into {_t(catalog)} values (1, 2)")
        runner.execute(f"alter table {_t(catalog)} drop column b")
        assert runner.execute(f"select * from {_t(catalog)}").rows == [(1,)]

    def test_insert_select_from_self(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (k bigint)")
        runner.execute(f"insert into {_t(catalog)} values (1), (2)")
        runner.execute(
            f"insert into {_t(catalog)} select k + 10 from {_t(catalog)}"
        )
        assert sorted(
            runner.execute(f"select k from {_t(catalog)}").rows
        ) == [(1,), (2,), (11,), (12,)]

    def test_delete_all_then_reinsert(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (k bigint)")
        runner.execute(f"insert into {_t(catalog)} values (1), (2)")
        runner.execute(f"delete from {_t(catalog)}")
        assert runner.execute(
            f"select count(*) from {_t(catalog)}"
        ).rows == [(0,)]
        runner.execute(f"insert into {_t(catalog)} values (9)")
        assert runner.execute(f"select * from {_t(catalog)}").rows == [(9,)]

    def test_long_decimal_roundtrip(self, runner, catalog):
        from decimal import Decimal

        runner.execute(f"create table {_t(catalog)} (v decimal(38,2))")
        runner.execute(
            f"insert into {_t(catalog)} values "
            "(decimal '99999999999999999999.25'), (null)"
        )
        assert sorted(
            runner.execute(f"select * from {_t(catalog)}").rows,
            key=lambda r: (r[0] is not None, r[0]),
        ) == [(None,), (Decimal("99999999999999999999.25"),)]

    def test_timestamp_roundtrip(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (ts timestamp)")
        runner.execute(
            f"insert into {_t(catalog)} values "
            "(timestamp '2021-07-15 13:14:15.250')"
        )
        assert runner.execute(f"select * from {_t(catalog)}").rows == [
            (datetime.datetime(2021, 7, 15, 13, 14, 15, 250000),)
        ]

    def test_duplicate_create_rejected(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (k bigint)")
        with pytest.raises(Exception):
            runner.execute(f"create table {_t(catalog)} (k bigint)")
        # IF NOT EXISTS form must not raise
        runner.execute(
            f"create table if not exists {_t(catalog)} (k bigint)"
        )

    def test_merge_upsert(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (k bigint, v bigint)")
        runner.execute(f"insert into {_t(catalog)} values (1, 10), (2, 20)")
        runner.execute(
            f"merge into {_t(catalog)} t using (values (2, 200), (3, 300)) "
            "s(k, v) on t.k = s.k "
            "when matched then update set v = s.v "
            "when not matched then insert values (s.k, s.v)"
        )
        assert sorted(
            runner.execute(f"select * from {_t(catalog)}").rows
        ) == [(1, 10), (2, 200), (3, 300)]

    def test_view_over_connector_table(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (k bigint)")
        runner.execute(f"insert into {_t(catalog)} values (1), (2)")
        runner.execute(
            f"create view memory.default.conf_v as "
            f"select k * 2 d from {_t(catalog)}"
        )
        try:
            assert sorted(
                runner.execute("select d from memory.default.conf_v").rows
            ) == [(2,), (4,)]
        finally:
            runner.execute("drop view memory.default.conf_v")

    def test_unicode_strings(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (s varchar)")
        runner.execute(
            f"insert into {_t(catalog)} values ('héllo'), ('日本語'), ('')"
        )
        rows = sorted(runner.execute(f"select s from {_t(catalog)}").rows)
        assert rows == [("",), ("héllo",), ("日本語",)]
        assert runner.execute(
            f"select length(s) from {_t(catalog)} where s = '日本語'"
        ).rows == [(3,)]


@pytest.mark.parametrize("catalog,schema,table,expected", READ_ONLY)
class TestReadOnlyConnectorExtended:
    def test_limit_pushdown_shape(self, runner, catalog, schema, table, expected):
        rows = runner.execute(
            f"select * from {catalog}.{schema}.{table} limit 3"
        ).rows
        assert len(rows) == 3

    def test_order_by_first_column(self, runner, catalog, schema, table, expected):
        pk = runner.execute(
            f"show columns from {catalog}.{schema}.{table}"
        ).rows[0][0]
        rows = runner.execute(
            f"select {pk} from {catalog}.{schema}.{table} order by {pk}"
        ).rows
        vals = [r[0] for r in rows]
        assert vals == sorted(vals) and len(vals) == expected

    def test_describe_matches_select_star(self, runner, catalog, schema, table, expected):
        cols = runner.execute(
            f"show columns from {catalog}.{schema}.{table}"
        ).rows
        res = runner.execute(
            f"select * from {catalog}.{schema}.{table} limit 1"
        )
        assert [c[0] for c in cols] == list(res.column_names)

    def test_ddl_rejected(self, runner, catalog, schema, table, expected):
        with pytest.raises(Exception):
            runner.execute(f"drop table {catalog}.{schema}.{table}")
        with pytest.raises(Exception):
            runner.execute(
                f"delete from {catalog}.{schema}.{table}"
            )


class TestIcebergSnapshots:
    """Iceberg-analog specific: snapshots, time travel, metadata tables,
    write conflict (BaseIcebergConnectorTest analogs)."""

    def test_snapshot_history_grows(self, runner):
        runner.execute("create table iceberg.default.snap_t (k bigint)")
        runner.execute("insert into iceberg.default.snap_t values (1)")
        runner.execute("insert into iceberg.default.snap_t values (2)")
        hist = runner.execute(
            'select * from iceberg.default."snap_t$history"'
        ).rows
        assert len(hist) >= 2

    def test_time_travel_reads_old_snapshot(self, runner):
        runner.execute("create table iceberg.default.tt_t (k bigint)")
        runner.execute("insert into iceberg.default.tt_t values (1)")
        snaps = runner.execute(
            'select * from iceberg.default."tt_t$snapshots"'
        ).rows
        first_snapshot = snaps[-1][0]
        runner.execute("insert into iceberg.default.tt_t values (2)")
        assert runner.execute(
            "select count(*) from iceberg.default.tt_t"
        ).only_value() == 2
        # the OLD snapshot must still read one row
        old_count = runner.execute(
            f'select count(*) from iceberg.default."tt_t@{first_snapshot}"'
        ).only_value()
        assert old_count == 1

    def test_files_metadata_table(self, runner):
        runner.execute("create table iceberg.default.files_t (k bigint)")
        runner.execute("insert into iceberg.default.files_t values (1)")
        files = runner.execute(
            'select * from iceberg.default."files_t$files"'
        ).rows
        assert len(files) >= 1
