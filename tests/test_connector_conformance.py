"""Shared connector conformance suite.

Reference role: testing/trino-testing's BaseConnectorTest — ONE battery of
behavioral checks every connector must pass, parameterized over the
connectors instead of copy-pasted per plugin.  Writable connectors run the
full DML battery; generator-backed connectors run the read battery.
"""

import datetime

import pytest

pytestmark = pytest.mark.smoke

WRITABLE = ["memory", "iceberg"]
READ_ONLY = [("tpch", "tiny", "nation", 25), ("tpcds", "tiny", "reason", 35)]


@pytest.fixture()
def runner(request, tmp_path):
    """LocalQueryRunner with every conformance-tested catalog mounted."""
    from trino_tpu.connectors.api import default_catalogs
    from trino_tpu.connectors.iceberg import IcebergConnector
    from trino_tpu.runtime.runner import LocalQueryRunner

    cm = default_catalogs()
    cm.register("iceberg", IcebergConnector(str(tmp_path / "warehouse")))
    return LocalQueryRunner(
        catalogs=cm, catalog="memory", schema="default", target_splits=2
    )


def _t(catalog):
    return f"{catalog}.default.conf_t"


@pytest.mark.parametrize("catalog", WRITABLE)
class TestWritableConnector:
    """The write-path battery (BaseConnectorTest testCreateTable /
    testInsert / testDelete / testUpdate analogs)."""

    def test_create_insert_select(self, runner, catalog):
        runner.execute(
            f"create table {_t(catalog)} (k bigint, s varchar, d double)"
        )
        runner.execute(
            f"insert into {_t(catalog)} values "
            "(1, 'a', 1.5), (2, 'b', 2.5), (3, null, null)"
        )
        rows = sorted(runner.execute(f"select * from {_t(catalog)}").rows)
        assert rows == [(1, "a", 1.5), (2, "b", 2.5), (3, None, None)]

    def test_predicate_and_agg(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (k bigint, v double)")
        runner.execute(
            f"insert into {_t(catalog)} values (1, 10.0), (1, 20.0), (2, 5.0)"
        )
        assert runner.execute(
            f"select k, sum(v) from {_t(catalog)} where v > 6 "
            "group by k order by k"
        ).rows == [(1, 30.0)]

    def test_join_with_fixture(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (rk bigint)")
        runner.execute(f"insert into {_t(catalog)} values (0), (2)")
        rows = runner.execute(
            f"select r.r_name from {_t(catalog)} t "
            "join tpch.tiny.region r on t.rk = r.r_regionkey order by 1"
        ).rows
        assert rows == [("AFRICA",), ("ASIA",)]

    def test_delete_update(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (k bigint, v bigint)")
        runner.execute(
            f"insert into {_t(catalog)} values (1, 10), (2, 20), (3, 30)"
        )
        runner.execute(f"delete from {_t(catalog)} where k = 2")
        runner.execute(f"update {_t(catalog)} set v = v + 1 where k = 3")
        assert sorted(runner.execute(f"select * from {_t(catalog)}").rows) == [
            (1, 10), (3, 31),
        ]

    def test_types_roundtrip(self, runner, catalog):
        runner.execute(
            f"create table {_t(catalog)} "
            "(b boolean, i integer, x bigint, r double, "
            "dec decimal(10,2), dt date, s varchar)"
        )
        runner.execute(
            f"insert into {_t(catalog)} values "
            "(true, 7, 9000000000, 1.25, 3.50, date '2020-02-29', 'z')"
        )
        row = runner.execute(f"select * from {_t(catalog)}").rows[0]
        assert row[0] is True and row[1] == 7 and row[2] == 9000000000
        assert row[3] == 1.25 and float(row[4]) == 3.5
        assert row[5] == datetime.date(2020, 2, 29) and row[6] == "z"

    def test_show_columns_and_drop(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (k bigint, s varchar)")
        cols = runner.execute(f"show columns from {_t(catalog)}").rows
        assert [c[0] for c in cols] == ["k", "s"]
        runner.execute(f"drop table {_t(catalog)}")
        tables = runner.execute(f"show tables from {catalog}.default").rows
        assert ("conf_t",) not in tables

    def test_insert_column_subset(self, runner, catalog):
        runner.execute(f"create table {_t(catalog)} (a bigint, b varchar)")
        runner.execute(f"insert into {_t(catalog)} (b) values ('only-b')")
        assert runner.execute(f"select * from {_t(catalog)}").rows == [
            (None, "only-b")
        ]


@pytest.mark.parametrize("catalog,schema,table,expected", READ_ONLY)
class TestReadOnlyConnector:
    """Generator/fixture connector battery (AbstractTestQueries-style)."""

    def test_count(self, runner, catalog, schema, table, expected):
        assert runner.execute(
            f"select count(*) from {catalog}.{schema}.{table}"
        ).rows == [(expected,)]

    def test_predicate_scan(self, runner, catalog, schema, table, expected):
        total = runner.execute(
            f"select count(*) from {catalog}.{schema}.{table}"
        ).only_value()
        pk = runner.execute(
            f"show columns from {catalog}.{schema}.{table}"
        ).rows[0][0]
        some = runner.execute(
            f"select count(*) from {catalog}.{schema}.{table} where {pk} >= 1"
        ).only_value()
        assert 0 < some <= total

    def test_stats_present(self, runner, catalog, schema, table, expected):
        rows = runner.execute(
            f"show stats for {catalog}.{schema}.{table}"
        ).rows
        summary = [r for r in rows if r[0] is None]
        assert summary and summary[0][4] == float(expected)

    def test_writes_rejected(self, runner, catalog, schema, table, expected):
        with pytest.raises(Exception):
            runner.execute(
                f"insert into {catalog}.{schema}.{table} values (1)"
            )
