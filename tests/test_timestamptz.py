"""TIMESTAMP WITH TIME ZONE tests (reference: TestTimestampWithTimeZone.java,
operator/scalar/DateTimeFunctions.java, spi DateTimeEncoding packing)."""

import datetime

import pytest

pytestmark = pytest.mark.smoke


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime.runner import LocalQueryRunner

    return LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=2)


def test_tz_literal_roundtrip(runner):
    rows = runner.execute("select timestamp '2020-03-01 10:30:00 +05:30'").rows
    v = rows[0][0]
    assert v == datetime.datetime(
        2020, 3, 1, 10, 30,
        tzinfo=datetime.timezone(datetime.timedelta(minutes=330)),
    )


def test_tz_equality_is_by_instant(runner):
    rows = runner.execute(
        "select timestamp '2020-01-01 00:00:00 +02:00' = "
        "timestamp '2019-12-31 22:00:00 +00:00'"
    ).rows
    assert rows == [(True,)]


def test_at_time_zone(runner):
    rows = runner.execute(
        "select timestamp '2020-03-01 10:30:00 +05:30' at time zone 'UTC'"
    ).rows
    assert rows[0][0] == datetime.datetime(
        2020, 3, 1, 5, 0, tzinfo=datetime.timezone.utc
    )


def test_tz_casts(runner):
    rows = runner.execute(
        "select cast(timestamp '2020-03-01 10:30:00 +05:30' as timestamp), "
        "cast(timestamp '2020-03-01 23:30:00 +05:30' as date), "
        "cast(date '2020-03-01' as timestamp with time zone)"
    ).rows
    ts, d, tz = rows[0]
    # wall clock in the value's zone (ADVICE r4 fix), matching tz->date
    assert ts == datetime.datetime(2020, 3, 1, 10, 30)
    assert d == datetime.date(2020, 3, 1)
    assert tz == datetime.datetime(2020, 3, 1, tzinfo=datetime.timezone.utc)


def test_hour_minute_second(runner):
    rows = runner.execute(
        "select hour(t), minute(t), second(t), "
        "extract(hour from t), extract(minute from t) "
        "from (select timestamp '2020-03-01 10:30:45' t)"
    ).rows
    assert rows == [(10, 30, 45, 10, 30)]


def test_tz_hour_respects_zone(runner):
    rows = runner.execute(
        "select hour(timestamp '2020-03-01 22:30:00 +05:30')"
    ).rows
    assert rows == [(22,)]  # wall-clock hour in the value's zone


def test_timezone_hour_minute(runner):
    rows = runner.execute(
        "select extract(timezone_hour from timestamp '2020-01-01 00:00:00 -08:30'), "
        "extract(timezone_minute from timestamp '2020-01-01 00:00:00 -08:30')"
    ).rows
    assert rows == [(-8, -30)]


def test_unixtime_round_trip(runner):
    rows = runner.execute(
        "select to_unixtime(timestamp '1970-01-01 01:00:00 +00:00'), "
        "from_unixtime(3600, '+01:00')"
    ).rows
    secs, tz = rows[0]
    assert secs == 3600.0
    assert tz == datetime.datetime(
        1970, 1, 1, 2, 0,
        tzinfo=datetime.timezone(datetime.timedelta(hours=1)),
    )


def test_current_timestamp_is_tz(runner):
    rows = runner.execute("select current_timestamp").rows
    v = rows[0][0]
    assert v.tzinfo is not None
    assert abs((datetime.datetime.now(datetime.timezone.utc) - v).total_seconds()) < 3600


def test_named_zone_literal(runner):
    rows = runner.execute(
        "select timestamp '2020-06-01 12:00:00 America/New_York'"
    ).rows
    v = rows[0][0]
    assert v.utcoffset() == datetime.timedelta(hours=-4)  # EDT


def test_tz_order_by(runner):
    rows = runner.execute(
        "select t from (values timestamp '2020-01-01 12:00:00 +05:00', "
        "timestamp '2020-01-01 10:00:00 +00:00', "
        "timestamp '2020-01-01 05:00:00 -03:00') as v(t) order by t"
    ).rows
    instants = [r[0].astimezone(datetime.timezone.utc) for r in rows]
    assert instants == sorted(instants)


def test_tz_cast_to_timestamp_keeps_wall_clock(runner):
    # ADVICE r4: cast(tz -> timestamp) keeps the wall clock in the value's
    # zone (reference DateTimeOperators), consistent with cast(tz -> date).
    rows = runner.execute(
        "select cast(timestamp '2020-03-01 10:30:00 +05:30' as timestamp)"
    ).rows
    assert rows == [(datetime.datetime(2020, 3, 1, 10, 30),)]
    # consistency: date(ts) == date(cast(ts as timestamp))
    rows = runner.execute(
        "select cast(timestamp '2020-03-01 01:30:00 +05:30' as date), "
        "cast(cast(timestamp '2020-03-01 01:30:00 +05:30' as timestamp) as date)"
    ).rows
    assert rows[0][0] == rows[0][1] == datetime.date(2020, 3, 1)


def test_tz_cast_wall_clock_non_constant():
    # same semantics through the compiled (column, non-folded) path
    from trino_tpu.runtime.runner import LocalQueryRunner

    r = LocalQueryRunner(catalog="memory", schema="default", target_splits=2)
    r.execute("create table tzc (x timestamp with time zone)")
    r.execute("insert into tzc values (timestamp '2020-03-01 10:30:00 +05:30')")
    rows = r.execute("select cast(x as timestamp) from tzc").rows
    assert rows == [(datetime.datetime(2020, 3, 1, 10, 30),)]
