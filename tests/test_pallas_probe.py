"""Pallas gather-probe kernel (ops/pallas_probe.py): lower/upper-bound
binary search over the sorted build canon, correctness vs the XLA probe
(`ops.join._locate_sorted`, the fallback and oracle), the single-plane
eligibility gate, and end-to-end behind the `pallas_probe` session
property.  On CPU the kernel runs in interpreter mode; the TPU path
compiles the same program text."""

import jax.numpy as jnp
import numpy as np
import pytest

from trino_tpu.ops.join import _locate_sorted
from trino_tpu.ops.pallas_probe import (
    locate_sorted_pallas,
    probe_kernel_eligible,
)

LINEITEM_ORDERS = (
    "tpch.tiny.lineitem:l_orderkey:8,tpch.tiny.orders:o_orderkey:8"
)


def _sorted_build(rng, cap_b, n_match, key_hi):
    """Build canon with the runner's invariant: matchable rows sorted in
    [0, n_match), tail padded with a large sentinel."""
    keys = np.sort(rng.integers(0, key_hi, n_match))
    pad = np.full(cap_b - n_match, np.iinfo(np.int64).max, dtype=np.int64)
    return jnp.asarray(np.concatenate([keys, pad]).astype(np.int64))


def _check_against_xla(build, n_match, probe, nomatch, cap_b, block=1024):
    start_p, count_p = locate_sorted_pallas(
        build, n_match, probe, nomatch, cap_b=cap_b, interpret=True,
        block=block,
    )
    start_x, count_x = _locate_sorted(
        [build], jnp.asarray(n_match, jnp.int64), [probe], nomatch,
        cap_b=cap_b,
    )
    assert np.array_equal(np.asarray(count_p), np.asarray(count_x))
    # starts only meaningful where a match run exists (count > 0) or the
    # oracle zeroes them (nomatch rows) — compare them everywhere anyway:
    # both implementations define start as the lower bound, zeroed on
    # nomatch, so they must agree bit-for-bit
    assert np.array_equal(np.asarray(start_p), np.asarray(start_x))


def test_kernel_matches_xla_with_duplicates_and_misses():
    rng = np.random.default_rng(11)
    cap_b, n_match = 512, 389
    build = _sorted_build(rng, cap_b, n_match, key_hi=64)  # heavy dup runs
    probe = jnp.asarray(rng.integers(-4, 72, 2048).astype(np.int64))
    nomatch = jnp.asarray(rng.random(2048) < 0.15)
    _check_against_xla(build, n_match, probe, nomatch, cap_b)


def test_kernel_multi_block_grid():
    rng = np.random.default_rng(12)
    cap_b, n_match = 128, 100
    build = _sorted_build(rng, cap_b, n_match, key_hi=1000)
    probe = jnp.asarray(rng.integers(0, 1000, 1024).astype(np.int64))
    nomatch = jnp.zeros(1024, bool)
    # block 256 -> 4 grid steps; each step re-reads the whole build canon
    _check_against_xla(build, n_match, probe, nomatch, cap_b, block=256)


def test_kernel_empty_build_and_all_nomatch():
    cap_b = 16
    build = jnp.full(cap_b, jnp.iinfo(jnp.int64).max, dtype=jnp.int64)
    probe = jnp.asarray(np.arange(64, dtype=np.int64))
    _check_against_xla(build, 0, probe, jnp.zeros(64, bool), cap_b)
    _check_against_xla(build, 0, probe, jnp.ones(64, bool), cap_b)


def test_eligibility_gate():
    i = jnp.asarray(np.arange(8, dtype=np.int64))
    f = jnp.asarray(np.arange(8, dtype=np.float64))
    assert probe_kernel_eligible([i], [i])
    # limb-coded (two-plane) long-decimal canon stays on the XLA path
    assert not probe_kernel_eligible([i, i], [i, i])
    # float canon (NaN semantics live outside the kernel's scope)
    assert not probe_kernel_eligible([f], [f])
    assert not probe_kernel_eligible([i], [f])


@pytest.mark.parametrize("qid", [3, 5])
def test_mesh_query_with_pallas_probe_matches_local(qid):
    from trino_tpu.connectors.tpch.queries import QUERIES
    from trino_tpu.parallel import DistributedQueryRunner
    from trino_tpu.runtime.runner import LocalQueryRunner

    sql = QUERIES[qid]
    expected = LocalQueryRunner(catalog="tpch", schema="tiny").execute(sql)
    dist = DistributedQueryRunner(n_workers=8, catalog="tpch", schema="tiny")
    dist.execute(f"set session table_layouts = '{LINEITEM_ORDERS}'")
    dist.execute("set session pallas_probe = true")
    res = dist.execute(sql)
    assert sorted(res.rows) == sorted(expected.rows)
