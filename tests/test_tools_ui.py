"""Table functions, SHOW FUNCTIONS/SESSION, web UI, proxy, verifier
(reference: spi/function/table + SequenceFunction/ExcludeColumnsFunction,
webapp UI resources, client/trino-proxy, service/trino-verifier)."""

import json
import urllib.request

import pytest

from trino_tpu.runtime.runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=2)


def q(runner, sql):
    return runner.execute(sql).rows


# -- table functions ----------------------------------------------------------


def test_table_function_sequence(runner):
    assert q(runner, "SELECT count(*), sum(sequential_number) FROM TABLE(sequence(1, 100))") == [
        (100, 5050)
    ]
    assert q(runner, "SELECT * FROM TABLE(sequence(1, 10, 3))") == [
        (1,), (4,), (7,), (10,)
    ]


def test_table_function_named_args(runner):
    assert q(
        runner, "SELECT s FROM TABLE(sequence(start => 1, stop => 3)) t(s)"
    ) == [(1,), (2,), (3,)]


def test_table_function_exclude_columns(runner):
    res = q(
        runner,
        "SELECT * FROM TABLE(exclude_columns(TABLE(nation), "
        "DESCRIPTOR(n_comment, n_regionkey))) LIMIT 2",
    )
    assert res == [(0, "ALGERIA"), (1, "ARGENTINA")]


def test_table_function_unknown(runner):
    from trino_tpu.planner.analyzer import AnalysisError

    with pytest.raises(AnalysisError, match="table function not found"):
        q(runner, "SELECT * FROM TABLE(nope(1))")


# -- SHOW FUNCTIONS / SESSION -------------------------------------------------


def test_show_functions(runner):
    rows = q(runner, "SHOW FUNCTIONS")
    names = {r[0] for r in rows}
    kinds = {r[0]: r[3] for r in rows}
    assert {"sum", "split", "row_number", "sequence"} <= names
    assert kinds["sum"] == "aggregate"
    assert kinds["row_number"] == "window"
    assert kinds["sequence"] == "table"
    assert kinds["split"] == "scalar"


def test_show_functions_like(runner):
    rows = q(runner, "SHOW FUNCTIONS LIKE 'json%'")
    assert {r[0] for r in rows} == {
        "json_array_length", "json_extract", "json_extract_scalar",
        "json_format", "json_parse", "json_size",
    }


def test_show_session(runner):
    rows = q(runner, "SHOW SESSION")
    names = {r[0] for r in rows}
    assert {"target_splits", "retry_policy", "scan_cache"} <= names


# -- web UI -------------------------------------------------------------------


def test_web_ui():
    from trino_tpu.server.coordinator import CoordinatorServer

    srv = CoordinatorServer(port=0)
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        qq = srv.submit("select count(*) from nation")
        qq.done.wait(timeout=60)
        page = urllib.request.urlopen(f"{base}/ui/", timeout=10).read()
        assert b"trino_tpu coordinator" in page
        stats = json.load(urllib.request.urlopen(f"{base}/ui/api/stats", timeout=10))
        assert stats["totalQueries"] >= 1
        queries = json.load(urllib.request.urlopen(f"{base}/ui/api/query", timeout=10))
        assert any(x["queryId"] == qq.id for x in queries)
        one = json.load(
            urllib.request.urlopen(f"{base}/ui/api/query/{qq.id}", timeout=10)
        )
        assert one["state"] == "FINISHED" and one["rowCount"] == 1
    finally:
        srv.shutdown()


# -- proxy --------------------------------------------------------------------


def test_proxy_roundtrip():
    from trino_tpu.client import Client
    from trino_tpu.server.coordinator import CoordinatorServer
    from trino_tpu.server.proxy import ProxyServer

    srv = CoordinatorServer(port=0)
    srv.start()
    proxy = ProxyServer(f"http://127.0.0.1:{srv.port}", port=0).start()
    try:
        cols, rows = Client(proxy.url).execute("select 41 + 1")
        assert rows == [(42,)]
    finally:
        proxy.shutdown()
        srv.shutdown()


# -- verifier -----------------------------------------------------------------


def test_verifier_match_and_mismatch(runner):
    from trino_tpu.testing.verifier import Verifier

    class Broken:
        def __init__(self, inner):
            self.inner = inner

        def execute(self, sql):
            res = self.inner.execute(sql)
            if "n_regionkey" in sql:
                res = type(res)(
                    res.column_names, [tuple(r) for r in res.rows[:-1]], res.types
                )
            return res

    control = LocalQueryRunner(catalog="tpch", schema="tiny")
    v = Verifier(control, runner)
    rep = v.run({"a": "select count(*) from nation", "b": "select 1.5"})
    assert rep.matched == 2 and not rep.failed

    v2 = Verifier(control, Broken(runner))
    rep2 = v2.run(
        {
            "ok": "select n_name from nation where n_nationkey = 0",
            "bad": "select n_regionkey from nation",
            "err": "select no_such_column from nation",
        }
    )
    st = {r.query_id: r.status for r in rep2.results}
    assert st == {"ok": "MATCH", "bad": "MISMATCH", "err": "CONTROL_ERROR"}


# -- lint: raw perf_counter phase timing --------------------------------------


def test_lint_flags_raw_perf_counter(tmp_path):
    import os
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo_root, "tools"))
    try:
        import lint_tpu
    finally:
        sys.path.pop(0)
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import time\n"
        "from time import perf_counter\n"
        "def f():\n"
        "    t0 = time.perf_counter()\n"
        "    t1 = perf_counter()\n"
        "    return t1 - t0\n"
    )
    findings = [
        f for f in lint_tpu.lint_file(str(bad))
        if f.rule == "raw-perf-counter"
    ]
    assert len(findings) == 2
    ok = tmp_path / "ok.py"
    ok.write_text(
        "from trino_tpu.telemetry import now\n"
        "def f():\n"
        "    return now()\n"
        "def boundary():  # lint: allow(raw-perf-counter)\n"
        "    import time\n"
        "    return time.perf_counter()\n"
    )
    assert [
        f for f in lint_tpu.lint_file(str(ok))
        if f.rule == "raw-perf-counter"
    ] == []


# -- lint: telemetry discipline (stray registries, ledger bypasses) -----------


def _lint_tpu():
    import os
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo_root, "tools"))
    try:
        import lint_tpu
    finally:
        sys.path.pop(0)
    return lint_tpu


def test_lint_flags_stray_registry_and_ledger_bypass(tmp_path):
    lint_tpu = _lint_tpu()
    pkg = tmp_path / "trino_tpu"
    pkg.mkdir()
    (pkg / "bad.py").write_text(
        "from trino_tpu.telemetry.metrics import MetricsRegistry\n"
        "reg = MetricsRegistry()\n"
        "def smuggle(artifact, led):\n"
        "    artifact['decisions'] = led\n"
    )
    (pkg / "ok.py").write_text(
        "from trino_tpu.telemetry.metrics import REGISTRY\n"
        "c = REGISTRY.counter('x_total')\n"
        "def fine(artifact, led):\n"
        "    artifact['other'] = led\n"
        "def boundary():  # lint: allow(stray-metrics-registry)\n"
        "    from trino_tpu.telemetry.metrics import MetricsRegistry\n"
        "    return MetricsRegistry()\n"
    )
    findings, stale = lint_tpu.run_telemetry_discipline(
        str(tmp_path), baseline={}
    )
    rules = sorted(f.rule for f in findings)
    assert rules == ["ledger-bypass", "stray-metrics-registry"]
    assert all("bad.py" in f.file for f in findings)
    assert stale == []


def test_lint_telemetry_baseline_and_stale_detection(tmp_path):
    lint_tpu = _lint_tpu()
    pkg = tmp_path / "trino_tpu"
    pkg.mkdir()
    (pkg / "legacy.py").write_text(
        "from trino_tpu.telemetry.metrics import MetricsRegistry\n"
        "reg = MetricsRegistry()\n"
    )
    baseline = {
        "trino_tpu/legacy.py:stray-metrics-registry": "pre-ledger survivor",
        "trino_tpu/gone.py:ledger-bypass": "file was deleted",
    }
    findings, stale = lint_tpu.run_telemetry_discipline(
        str(tmp_path), baseline=baseline
    )
    assert findings == []  # triaged: baselined findings never fail
    assert stale == ["trino_tpu/gone.py:ledger-bypass"]  # honest baseline


def test_lint_telemetry_repo_is_triaged():
    """The shipped tree passes the telemetry-discipline pass with the
    shipped baseline, and the baseline holds no stale keys."""
    import os

    lint_tpu = _lint_tpu()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings, stale = lint_tpu.run_telemetry_discipline(repo_root)
    assert [f"{f.file}:{f.rule}" for f in findings] == []
    assert stale == []
