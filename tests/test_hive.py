"""Hive-style partitioned connector: parquet + ORC, partition pruning
(reference: plugin/trino-hive HivePartitionManager + page source factories)."""

import os

import pytest

from trino_tpu.connectors.api import CatalogManager, TableHandle
from trino_tpu.connectors.hive import HiveConnector, write_partitioned
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.runtime.runner import LocalQueryRunner


@pytest.fixture(scope="module", params=["parquet", "orc"])
def hive_root(request, tmp_path_factory):
    root = str(tmp_path_factory.mktemp(f"hive_{request.param}"))
    nparts = write_partitioned(
        TpchConnector(), "tiny", "nation", root,
        partition_by=["n_regionkey"], fmt=request.param,
    )
    assert nparts == 5
    return root


@pytest.fixture(scope="module")
def runner(hive_root):
    cm = CatalogManager()
    cm.register("hive", HiveConnector(hive_root))
    cm.register("tpch", TpchConnector())
    return LocalQueryRunner(cm, catalog="hive", schema="tiny", target_splits=4)


def test_hive_metadata(hive_root):
    conn = HiveConnector(hive_root)
    meta = conn.metadata().table_metadata("tiny", "nation")
    names = [c.name for c in meta.columns]
    assert "n_regionkey" in names and "n_name" in names
    assert conn.metadata().list_tables("tiny") == ["nation"]


def test_hive_full_scan_matches_generator(runner):
    hive_rows = runner.execute(
        "SELECT n_nationkey, n_name, n_regionkey FROM nation ORDER BY n_nationkey"
    ).rows
    tpch_rows = runner.execute(
        "SELECT n_nationkey, n_name, n_regionkey FROM tpch.tiny.nation "
        "ORDER BY n_nationkey"
    ).rows
    assert hive_rows == tpch_rows
    assert len(hive_rows) == 25


def test_hive_partition_pruning(runner, hive_root):
    conn = HiveConnector(hive_root)
    handle = TableHandle("hive", "tiny", "nation")
    all_splits = conn.splits(handle, target_splits=4)
    pruned = conn.splits(
        handle, target_splits=4, predicate=[("n_regionkey", "=", 2)]
    )
    assert len(pruned) < len(all_splits)
    # every pruned split carries only the matching partition value
    assert all(s.info[2]["n_regionkey"] == "2" for s in pruned)
    # and the engine gets correct results through the pruned scan
    rows = runner.execute(
        "SELECT count(*) FROM nation WHERE n_regionkey = 2"
    ).rows
    assert rows == [(5,)]


def test_hive_partition_range_pruning(runner, hive_root):
    conn = HiveConnector(hive_root)
    handle = TableHandle("hive", "tiny", "nation")
    pruned = conn.splits(
        handle, target_splits=4, predicate=[("n_regionkey", ">=", 3)]
    )
    vals = {s.info[2]["n_regionkey"] for s in pruned}
    assert vals == {"3", "4"}
    rows = runner.execute(
        "SELECT count(*) FROM nation WHERE n_regionkey >= 3"
    ).rows
    assert rows == [(10,)]


def test_hive_aggregation(runner):
    rows = runner.execute(
        "SELECT n_regionkey, count(*) FROM nation GROUP BY n_regionkey "
        "ORDER BY n_regionkey"
    ).rows
    assert rows == [(0, 5), (1, 5), (2, 5), (3, 5), (4, 5)]


def test_predicate_triples_extraction():
    from trino_tpu import types as T
    from trino_tpu.connectors.api import extract_predicate_triples
    from trino_tpu.expr import ir
    from trino_tpu.expr.ir import Form, Literal, SpecialForm, SymbolRef

    a = SymbolRef("a_0", T.BIGINT)
    b = SymbolRef("b_0", T.BIGINT)
    e = ir.and_(
        ir.comparison("=", a, Literal(3, T.BIGINT)),
        ir.comparison("<", Literal(5, T.BIGINT), b),
        SpecialForm(Form.IN, [a, Literal(1, T.BIGINT), Literal(2, T.BIGINT)]),
        SpecialForm(
            Form.BETWEEN, [b, Literal(0, T.BIGINT), Literal(9, T.BIGINT)]
        ),
    )
    triples = extract_predicate_triples(e, {"a_0": "a", "b_0": "b"})
    assert ("a", "=", 3) in triples
    assert ("b", ">", 5) in triples
    assert ("a", "in", (1, 2)) in triples
    assert ("b", ">=", 0) in triples and ("b", "<=", 9) in triples
