"""Authentication + access control (reference: server/security/
AuthenticationFilter, plugin password-file, file-based access control)."""

import base64
import json
import urllib.error
import urllib.request

import pytest

pytestmark = pytest.mark.smoke

from trino_tpu.runtime.runner import LocalQueryRunner
from trino_tpu.server.security import (
    AccessDeniedError,
    AccessRule,
    PasswordAuthenticator,
    RuleBasedAccessControl,
)


def test_password_authenticator():
    auth = PasswordAuthenticator({"alice": "secret"})
    assert auth.authenticate("alice", "secret")
    assert not auth.authenticate("alice", "wrong")
    assert not auth.authenticate("bob", "secret")


def test_password_file(tmp_path):
    p = tmp_path / "password.db"
    p.write_text("# users\nalice:s3cret\nbob:hunter2\n")
    auth = PasswordAuthenticator.from_file(str(p))
    assert auth.authenticate("bob", "hunter2")
    assert not auth.authenticate("bob", "nope")


def test_rule_based_select_control():
    ac = RuleBasedAccessControl(
        [
            AccessRule(user="alice", catalog="tpch", privileges=("SELECT",)),
            AccessRule(user="admin"),
        ]
    )
    ac.check_can_select("alice", "tpch", "tiny", "nation")
    ac.check_can_write("admin", "memory", "default", "t")
    with pytest.raises(AccessDeniedError):
        ac.check_can_select("alice", "memory", "default", "t")  # no rule
    with pytest.raises(AccessDeniedError):
        ac.check_can_write("alice", "tpch", "tiny", "nation")  # SELECT only


def test_runner_enforces_access_control():
    r = LocalQueryRunner(catalog="tpch", schema="tiny")
    r.access_control = RuleBasedAccessControl(
        [AccessRule(user="alice", catalog="tpch", table="nation")]
    )
    r.user = "alice"
    assert r.execute("select count(*) from nation").rows == [(25,)]
    with pytest.raises(AccessDeniedError):
        r.execute("select count(*) from region")
    # scans hidden inside CTEs/subqueries are still checked
    with pytest.raises(AccessDeniedError):
        r.execute(
            "with x as (select * from region) select count(*) from x"
        )


def test_runner_blocks_writes():
    r = LocalQueryRunner(catalog="memory", schema="default")
    r.access_control = RuleBasedAccessControl(
        [AccessRule(user="reader", privileges=("SELECT",))]
    )
    r.user = "reader"
    with pytest.raises(AccessDeniedError):
        r.execute("create table t (x bigint)")


def test_coordinator_basic_auth():
    from trino_tpu.server.coordinator import CoordinatorServer

    auth = PasswordAuthenticator({"alice": "pw"})
    srv = CoordinatorServer(port=0, authenticator=auth)
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"

        def post(creds):
            req = urllib.request.Request(
                f"{base}/v1/statement", data=b"select 1", method="POST"
            )
            if creds:
                req.add_header(
                    "Authorization",
                    "Basic " + base64.b64encode(creds.encode()).decode(),
                )
            return urllib.request.urlopen(req, timeout=10)

        with pytest.raises(urllib.error.HTTPError) as ei:
            post(None)
        assert ei.value.code == 401
        with pytest.raises(urllib.error.HTTPError) as ei:
            post("alice:wrong")
        assert ei.value.code == 401
        doc = json.load(post("alice:pw"))
        assert doc["stats"]["state"] in ("QUEUED", "RUNNING", "FINISHED")
        # the UI and result-paging GETs must not bypass authentication
        for path in ("/ui/api/query", "/ui/", "/v1/statement/executing/x/0"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{base}{path}", timeout=10)
            assert ei.value.code == 401, path
        req = urllib.request.Request(f"{base}/ui/api/stats")
        req.add_header(
            "Authorization",
            "Basic " + base64.b64encode(b"alice:pw").decode(),
        )
        stats = json.load(urllib.request.urlopen(req, timeout=10))
        assert stats["totalQueries"] >= 1
    finally:
        srv.shutdown()


@pytest.mark.smoke
def test_current_user():
    from trino_tpu.runtime.runner import LocalQueryRunner

    r = LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=2)
    assert r.execute("select current_user").rows == [("user",)]
    r.user = "alice"
    assert r.execute("select current_user").rows == [("alice",)]
