"""Predicate pushdown rule tests (reference: PredicatePushDown.java's
union/project/aggregation handling + TestPredicatePushdown)."""

import pytest

pytestmark = pytest.mark.smoke


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime.runner import LocalQueryRunner

    return LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=2)


def test_filter_through_union_reaches_scans(runner):
    txt = runner.explain(
        "select * from (select n_nationkey k from nation "
        "union all select r_regionkey from region) where k < 3"
    )
    # no residual Filter nodes: both branches push into their scans
    assert "Filter" not in txt
    assert txt.count("pushed=") == 2


def test_union_pushdown_results(runner):
    rows = sorted(
        runner.execute(
            "select * from (select n_nationkey k from nation "
            "union all select r_regionkey from region) where k < 3"
        ).rows
    )
    assert rows == [(0,), (0,), (1,), (1,), (2,), (2,)]


def test_having_on_group_key_pushes_below_agg(runner):
    txt = runner.explain(
        "select n_regionkey, count(*) c from nation "
        "group by n_regionkey having n_regionkey < 2"
    )
    assert "pushed=" in txt and "Filter" not in txt
    rows = runner.execute(
        "select n_regionkey, count(*) c from nation "
        "group by n_regionkey having n_regionkey < 2 order by 1"
    ).rows
    assert rows == [(0, 5), (1, 5)]


def test_having_on_aggregate_stays_above(runner):
    rows = runner.execute(
        "select n_regionkey, count(*) c from nation "
        "group by n_regionkey having count(*) > 4 order by 1"
    ).rows
    assert len(rows) == 5  # every region has 5 nations


def test_filter_through_computed_project(runner):
    rows = runner.execute(
        "select k2 from (select n_nationkey * 2 as k2 from nation) "
        "where k2 <= 4 order by 1"
    ).rows
    assert rows == [(0,), (2,), (4,)]


def test_union_pushdown_coerced_branch_types(runner):
    """date-unioned-with-timestamp branches must compare in the COERCED
    type: the pushed predicate carries the union's cast (and constant
    folding converts date->timestamp literals by unit, not bit reuse)."""
    rows = runner.execute(
        "select * from (select date '2024-01-02' d "
        "union all select timestamp '2024-01-01 00:00:00' d) "
        "where d > timestamp '2024-01-01 12:00:00'"
    ).rows
    import datetime

    assert rows == [(datetime.datetime(2024, 1, 2, 0, 0),)]


def _explain(runner, sql: str) -> str:
    return "\n".join(r[0] for r in runner.execute("explain " + sql).rows)


def test_rule_fire_stats_in_explain(runner):
    text = _explain(
        runner,
        "select c_name from (select * from customer order by c_custkey) t "
        "where c_custkey < 5 limit 3",
    )
    assert "rule fires:" in text


def test_trivial_filter_removed(runner):
    text = _explain(runner, "select n_name from nation where 1 = 1")
    assert "Filter" not in text


def test_false_filter_becomes_empty_values(runner):
    text = _explain(runner, "select n_name from nation where 1 = 0")
    assert "Values" in text and "TableScan" not in text
    assert runner.execute(
        "select n_name from nation where 1 = 0"
    ).rows == []


def test_merge_limits(runner):
    rows = runner.execute(
        "select * from (select n_name from nation limit 10) t limit 3"
    ).rows
    assert len(rows) == 3
    text = _explain(
        runner, "select * from (select n_name from nation limit 10) t limit 3"
    )
    assert text.count("Limit") + text.count("TopN") <= 1


def test_redundant_sort_under_aggregation_removed(runner):
    text = _explain(
        runner,
        "select x, count(*) from "
        "(select n_regionkey x from nation order by n_name) t group by x",
    )
    assert "Sort" not in text


def test_redundant_distinct_removed(runner):
    text = _explain(
        runner,
        "select distinct x from "
        "(select n_regionkey x from nation group by n_regionkey) t",
    )
    # one aggregation, not two
    assert text.count("Aggregation") == 1


def test_limit_over_values_folds(runner):
    text = _explain(runner, "select * from (values 1, 2, 3) t(x) limit 2")
    assert "Limit" not in text
    assert runner.execute(
        "select * from (values 1, 2, 3) t(x) limit 2"
    ).rows == [(1,), (2,)]
