"""Predicate pushdown rule tests (reference: PredicatePushDown.java's
union/project/aggregation handling + TestPredicatePushdown)."""

import pytest

pytestmark = pytest.mark.smoke


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime.runner import LocalQueryRunner

    return LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=2)


def test_filter_through_union_reaches_scans(runner):
    txt = runner.explain(
        "select * from (select n_nationkey k from nation "
        "union all select r_regionkey from region) where k < 3"
    )
    # no residual Filter nodes: both branches push into their scans
    assert "Filter" not in txt
    assert txt.count("pushed=") == 2


def test_union_pushdown_results(runner):
    rows = sorted(
        runner.execute(
            "select * from (select n_nationkey k from nation "
            "union all select r_regionkey from region) where k < 3"
        ).rows
    )
    assert rows == [(0,), (0,), (1,), (1,), (2,), (2,)]


def test_having_on_group_key_pushes_below_agg(runner):
    txt = runner.explain(
        "select n_regionkey, count(*) c from nation "
        "group by n_regionkey having n_regionkey < 2"
    )
    assert "pushed=" in txt and "Filter" not in txt
    rows = runner.execute(
        "select n_regionkey, count(*) c from nation "
        "group by n_regionkey having n_regionkey < 2 order by 1"
    ).rows
    assert rows == [(0, 5), (1, 5)]


def test_having_on_aggregate_stays_above(runner):
    rows = runner.execute(
        "select n_regionkey, count(*) c from nation "
        "group by n_regionkey having count(*) > 4 order by 1"
    ).rows
    assert len(rows) == 5  # every region has 5 nations


def test_filter_through_computed_project(runner):
    rows = runner.execute(
        "select k2 from (select n_nationkey * 2 as k2 from nation) "
        "where k2 <= 4 order by 1"
    ).rows
    assert rows == [(0,), (2,), (4,)]


def test_union_pushdown_coerced_branch_types(runner):
    """date-unioned-with-timestamp branches must compare in the COERCED
    type: the pushed predicate carries the union's cast (and constant
    folding converts date->timestamp literals by unit, not bit reuse)."""
    rows = runner.execute(
        "select * from (select date '2024-01-02' d "
        "union all select timestamp '2024-01-01 00:00:00' d) "
        "where d > timestamp '2024-01-01 12:00:00'"
    ).rows
    import datetime

    assert rows == [(datetime.datetime(2024, 1, 2, 0, 0),)]
