"""Event listener + failure injection/retry tests (reference style:
TestEventListener + BaseFailureRecoveryTest)."""

import pytest

from trino_tpu.runtime.events import CollectingEventListener
from trino_tpu.runtime.retry import FAILURE_INJECTOR, InjectedFailure
from trino_tpu.runtime.runner import LocalQueryRunner


@pytest.fixture()
def runner():
    FAILURE_INJECTOR.clear()
    r = LocalQueryRunner()
    yield r
    FAILURE_INJECTOR.clear()


def test_events_on_success(runner):
    listener = CollectingEventListener()
    runner.events.add(listener)
    runner.execute("select count(*) from region")
    assert len(listener.created) == 1
    done = listener.completed[0]
    assert done.state == "FINISHED" and done.rows == 1
    assert done.wall_s >= 0


def test_events_on_failure(runner):
    listener = CollectingEventListener()
    runner.events.add(listener)
    with pytest.raises(Exception):
        runner.execute("select bogus_col from region")
    assert listener.completed[0].state == "FAILED"
    assert "bogus_col" in listener.completed[0].error


def test_injected_failure_fails_without_retry(runner):
    FAILURE_INJECTOR.inject("scan:tiny.nation", times=1)
    with pytest.raises(InjectedFailure):
        runner.execute("select count(*) from nation")


def test_query_retry_recovers(runner):
    FAILURE_INJECTOR.inject("scan:tiny.nation", times=2)
    runner.execute("set session retry_policy = 'QUERY'")
    res = runner.execute("select count(*) from nation")
    assert res.rows == [(25,)]


def test_retry_exhaustion(runner):
    FAILURE_INJECTOR.inject("scan:tiny.nation", times=100)
    runner.execute("set session retry_policy = 'QUERY'")
    with pytest.raises(InjectedFailure):
        runner.execute("select count(*) from nation")
