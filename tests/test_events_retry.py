"""Event listener + failure injection/retry tests (reference style:
TestEventListener + BaseFailureRecoveryTest)."""

import pytest

from trino_tpu.runtime.events import CollectingEventListener
from trino_tpu.runtime.retry import FAILURE_INJECTOR, InjectedFailure
from trino_tpu.runtime.runner import LocalQueryRunner


@pytest.fixture()
def runner():
    FAILURE_INJECTOR.clear()
    r = LocalQueryRunner()
    yield r
    FAILURE_INJECTOR.clear()


def test_events_on_success(runner):
    listener = CollectingEventListener()
    runner.events.add(listener)
    runner.execute("select count(*) from region")
    assert len(listener.created) == 1
    done = listener.completed[0]
    assert done.state == "FINISHED" and done.rows == 1
    assert done.wall_s >= 0


def test_events_on_failure(runner):
    listener = CollectingEventListener()
    runner.events.add(listener)
    with pytest.raises(Exception):
        runner.execute("select bogus_col from region")
    assert listener.completed[0].state == "FAILED"
    assert "bogus_col" in listener.completed[0].error


class _BrokenListener(CollectingEventListener):
    def query_completed(self, e):
        raise RuntimeError("sink is down")


def test_listener_failure_warns_once_and_does_not_propagate(runner, caplog):
    """A broken audit sink must be VISIBLE (one rate-limited warning per
    listener class per event type) without breaking queries or starving
    other listeners."""
    import logging

    broken = _BrokenListener()
    healthy = CollectingEventListener()
    runner.events.add(broken)
    runner.events.add(healthy)
    with caplog.at_level(logging.WARNING, logger="trino_tpu.events"):
        runner.execute("select count(*) from region")
        runner.execute("select count(*) from region")
    # queries succeeded, the healthy listener saw both completions
    assert len(healthy.completed) == 2
    warnings = [
        r for r in caplog.records
        if "_BrokenListener" in r.getMessage()
        and "query_completed" in r.getMessage()
    ]
    assert len(warnings) == 1, "warning must be rate-limited per class/event"
    # created events (which _BrokenListener handles fine) did not warn
    assert not any(
        "query_created" in r.getMessage() for r in caplog.records
    )


def test_error_classification_user_vs_internal(runner):
    from trino_tpu.runtime.events import classify_error
    from trino_tpu.planner.analyzer import AnalysisError
    from trino_tpu.sql.parser import parse_statement

    with pytest.raises(Exception) as ei:
        parse_statement("not sql at all")
    assert classify_error(ei.value) == "USER_ERROR"  # ParseError
    assert classify_error(AnalysisError("no such column")) == "USER_ERROR"
    assert classify_error(KeyError("missing table")) == "USER_ERROR"
    assert classify_error(NotImplementedError("stmt")) == "USER_ERROR"
    assert classify_error(RuntimeError("bug")) == "INTERNAL_ERROR"
    assert classify_error(ZeroDivisionError()) == "INTERNAL_ERROR"


def test_failed_event_carries_error_type_and_registry_counts(runner):
    from trino_tpu.telemetry import REGISTRY

    listener = CollectingEventListener()
    runner.events.add(listener)
    c = REGISTRY.counter("trino_tpu_queries_total")
    before = c.value(("FAILED", "USER_ERROR"))
    with pytest.raises(Exception):
        runner.execute("select bogus_col from region")
    done = listener.completed[-1]
    assert done.state == "FAILED"
    assert done.error_type == "USER_ERROR"
    assert c.value(("FAILED", "USER_ERROR")) == before + 1


def test_completed_event_statistics_payload(runner):
    listener = CollectingEventListener()
    runner.events.add(listener)
    runner.execute("select count(*) from region")
    st = listener.completed[-1].statistics
    assert st is not None and st.rows == 1 and st.wall_s > 0
    assert st.spans > 0  # query_trace defaults on


def test_injected_failure_fails_without_retry(runner):
    FAILURE_INJECTOR.inject("scan:tiny.nation", times=1)
    with pytest.raises(InjectedFailure):
        runner.execute("select count(*) from nation")


def test_query_retry_recovers(runner):
    FAILURE_INJECTOR.inject("scan:tiny.nation", times=2)
    runner.execute("set session retry_policy = 'QUERY'")
    res = runner.execute("select count(*) from nation")
    assert res.rows == [(25,)]


def test_retry_exhaustion(runner):
    FAILURE_INJECTOR.inject("scan:tiny.nation", times=100)
    runner.execute("set session retry_policy = 'QUERY'")
    with pytest.raises(InjectedFailure):
        runner.execute("select count(*) from nation")
