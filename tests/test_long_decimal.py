"""Long decimal (precision 19-38, two-limb i128) tests.

Reference: core/trino-spi/.../spi/type/Int128Math.java semantics +
TestDecimalOperators/TestDecimalAggregation coverage; round-4 verdict
Missing #3 (the silent precision>18 clamp was a wrong-results landmine).
"""

from decimal import Decimal

import pytest

pytestmark = pytest.mark.smoke


@pytest.fixture()
def runner():
    from trino_tpu.runtime.runner import LocalQueryRunner

    r = LocalQueryRunner(catalog="memory", schema="default", target_splits=2)
    r.execute("create table big (k bigint, v decimal(38,2))")
    r.execute(
        "insert into big values "
        "(1, decimal '12345678901234567890.12'), "
        "(1, decimal '98765432109876543210.88'), "
        "(2, decimal '-5.00'), (2, null)"
    )
    return r


def test_literal_roundtrip(runner):
    rows = runner.execute(
        "select cast('99999999999999999999999999999999999999' as decimal(38,0)), "
        "decimal '-12345678901234567890123456.789012'"
    ).rows
    assert rows == [
        (
            Decimal("99999999999999999999999999999999999999"),
            Decimal("-12345678901234567890123456.789012"),
        )
    ]


def test_add_sub_exact(runner):
    rows = runner.execute(
        "select cast('99999999999999999999.25' as decimal(38,2)) + "
        "cast('0.75' as decimal(38,2)), "
        "cast('10000000000000000000.00' as decimal(38,2)) - "
        "cast('0.01' as decimal(38,2))"
    ).rows
    assert rows == [
        (Decimal("100000000000000000000.00"), Decimal("9999999999999999999.99"))
    ]


def test_negation_and_compare(runner):
    rows = runner.execute(
        "select -cast('12345678901234567890.12' as decimal(38,2)), "
        "cast('12345678901234567890.12' as decimal(38,2)) > "
        "cast('12345678901234567890.11' as decimal(38,2))"
    ).rows
    assert rows == [(Decimal("-12345678901234567890.12"), True)]


def test_ctas_scan_roundtrip(runner):
    assert sorted(
        runner.execute("select v from big where v is not null").rows
    ) == [
        (Decimal("-5.00"),),
        (Decimal("12345678901234567890.12"),),
        (Decimal("98765432109876543210.88"),),
    ]


def test_grouped_sum_exact(runner):
    rows = runner.execute(
        "select k, sum(v), count(v) from big group by k order by k"
    ).rows
    assert rows == [
        (1, Decimal("111111111011111111101.00"), 2),
        (2, Decimal("-5.00"), 1),
    ]


def test_global_agg_family(runner):
    rows = runner.execute(
        "select sum(v), avg(v), min(v), max(v) from big"
    ).rows
    assert rows == [
        (
            Decimal("111111111011111111096.00"),
            # 111111111011111111096.00 / 3, round half up at scale 2
            Decimal("37037037003703703698.67"),
            Decimal("-5.00"),
            Decimal("98765432109876543210.88"),
        )
    ]


def test_short_decimal_sum_widens_exactly(runner):
    # SUM over short decimals is typed decimal(38, s) with an exact Int128
    # state: 12 copies of 9e17 overflow i64 (1.08e19 > 9.2e18)
    runner.execute("create table w (v decimal(18,0))")
    runner.execute(
        "insert into w values " + ", ".join(["(900000000000000000)"] * 12)
    )
    rows = runner.execute("select sum(v) from w").rows
    assert rows == [(Decimal("10800000000000000000"),)]


def test_order_by_long(runner):
    rows = runner.execute(
        "select v from big order by v desc nulls last"
    ).rows
    assert rows == [
        (Decimal("98765432109876543210.88"),),
        (Decimal("12345678901234567890.12"),),
        (Decimal("-5.00"),),
        (None,),
    ]


def test_where_filter_long(runner):
    rows = runner.execute(
        "select k from big where v > decimal '12345678901234567890.11' "
        "order by v"
    ).rows
    assert rows == [(1,), (1,)]


def test_cast_long_to_short_and_back(runner):
    rows = runner.execute(
        "select cast(cast('123.45' as decimal(38,2)) as decimal(10,2)), "
        "cast(cast('123.45' as decimal(10,2)) as decimal(38,4))"
    ).rows
    assert rows == [(Decimal("123.45"), Decimal("123.4500"))]


def test_cast_long_to_double_and_varchar_literal(runner):
    rows = runner.execute(
        "select cast(cast('12345678901234567890.50' as decimal(38,2)) as double)"
    ).rows
    assert abs(rows[0][0] - 1.234567890123456789e19) < 1e5


def test_sum_distributed_partial_final():
    # the partial/final split must merge Int128 states exactly
    from trino_tpu.runtime.runner import LocalQueryRunner

    r = LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=4)
    rows = r.execute(
        "select sum(l_extendedprice) from lineitem"
    ).rows
    # engine-vs-pandas oracle
    from trino_tpu.testing import tpch_pandas

    li = tpch_pandas("tiny", "lineitem")
    expected = Decimal(str(li["l_extendedprice"].sum())).quantize(
        Decimal("0.01")
    )
    assert rows[0][0] == expected


def test_avg_rounding_half_up(runner):
    runner.execute("create table a2 (v decimal(38,2))")
    runner.execute(
        "insert into a2 values (decimal '0.01'), (decimal '0.02')"
    )
    # 0.03 / 2 = 0.015 -> rounds half away from zero to 0.02
    assert runner.execute("select avg(v) from a2").rows == [
        (Decimal("0.02"),)
    ]


def test_long_mul_div_mod(runner):
    rows = runner.execute(
        "select cast('12345678901234567890.12' as decimal(38,2)) * 2, "
        "cast('12345678901234567890.12' as decimal(38,2)) * decimal '-1.5', "
        "cast('12345678901234567890.12' as decimal(38,2)) % decimal '7.00'"
    ).rows
    assert rows[0][0] == Decimal("24691357802469135780.24")
    assert rows[0][1] == Decimal("-18518518351851851835.180")
    # 1234567890123456789012 % 700 = 412 -> 4.12
    assert rows[0][2] == Decimal((1234567890123456789012 % 700)).scaleb(-2)


def test_short_mul_widens_to_long(runner):
    # (18,0) * (18,0) types as decimal(36,0): product needs two limbs.
    # Expectation computed as an exact python int — Decimal * Decimal in
    # the default 28-digit context would ROUND the 36-digit product (the
    # engine's folder used to share that bug; the differential corpus in
    # tests/test_constant_fold_diff.py now keeps both exact)
    rows = runner.execute(
        "select cast(999999999999999999 as decimal(18,0)) * "
        "cast(999999999999999999 as decimal(18,0))"
    ).rows
    assert rows[0][0] == Decimal(999999999999999999**2)


def test_cast_negative_double_to_long(runner):
    rows = runner.execute(
        "select cast(-2.5e0 as decimal(38,1)), cast(-1e0 as decimal(38,2))"
    ).rows
    assert rows == [(Decimal("-2.5"), Decimal("-1.00"))]


def test_group_by_long_key_distributed_hash():
    from trino_tpu.runtime.runner import LocalQueryRunner

    r = LocalQueryRunner(catalog="memory", schema="default", target_splits=2)
    r.execute("create table gk (v decimal(38,2), n bigint)")
    r.execute(
        "insert into gk values (decimal '99999999999999999999.25', 1), "
        "(decimal '99999999999999999999.25', 2), (decimal '-5.00', 3)"
    )
    rows = r.execute(
        "select v, count(*), sum(n) from gk group by v order by v"
    ).rows
    assert rows == [
        (Decimal("-5.00"), 1, 3),
        (Decimal("99999999999999999999.25"), 2, 3),
    ]


def test_join_on_long_decimal_key(runner):
    runner.execute("create table j1 (k decimal(38,2), a bigint)")
    runner.execute("create table j2 (k decimal(38,2), b bigint)")
    runner.execute(
        "insert into j1 values (decimal '99999999999999999999.25', 1), "
        "(decimal '-5.00', 2)"
    )
    runner.execute(
        "insert into j2 values (decimal '99999999999999999999.25', 10), "
        "(decimal '7.00', 20)"
    )
    rows = runner.execute(
        "select a, b from j1 join j2 on j1.k = j2.k"
    ).rows
    assert rows == [(1, 10)]


def test_floor_ceil_round_abs_on_long_sum(runner):
    rows = runner.execute(
        "select floor(sum(v)), ceil(sum(v)), round(sum(v)), abs(min(v)) "
        "from big"
    ).rows
    # sum = 111111111011111111096.00
    assert rows == [
        (
            Decimal("111111111011111111096"),
            Decimal("111111111011111111096"),
            Decimal("111111111011111111096.00"),
            Decimal("5.00"),
        )
    ]
    rows = runner.execute(
        "select floor(v), ceil(v) from big where k = 2 and v is not null"
    ).rows
    assert rows == [(Decimal("-5"), Decimal("-5"))]
    rows = runner.execute(
        "select floor(cast('-2.5' as decimal(38,1))), "
        "ceil(cast('-2.5' as decimal(38,1))), "
        "round(cast('-2.5' as decimal(38,1)))"
    ).rows
    assert rows == [(Decimal("-3"), Decimal("-2"), Decimal("-3.0"))]


def test_greatest_least_long(runner):
    rows = runner.execute(
        "select greatest(max(v), decimal '5.00'), least(min(v), sum(v)) "
        "from big"
    ).rows
    assert rows == [
        (Decimal("98765432109876543210.88"), Decimal("-5.00"))
    ]


def test_union_long_with_bigint(runner):
    rows = runner.execute(
        "select v from (select v from big where k = 2 and v is not null "
        "union all select cast(3 as bigint)) t(v) order by v"
    ).rows
    assert rows == [(Decimal("-5.00"),), (Decimal("3.00"),)]


def test_window_functions_over_long(runner):
    runner.execute("create table wt (k bigint, v decimal(38,2))")
    runner.execute(
        "insert into wt values (1, decimal '99999999999999999999.25'), "
        "(2, decimal '99999999999999999999.25'), (3, decimal '-5.00')"
    )
    assert runner.execute(
        "select k, rank() over (order by v) from wt order by k"
    ).rows == [(1, 2), (2, 2), (3, 1)]
    assert runner.execute(
        "select k, count(*) over (partition by v) from wt order by k"
    ).rows == [(1, 2), (2, 2), (3, 1)]
    assert runner.execute(
        "select k, lag(v) over (order by k) from wt order by k"
    ).rows == [
        (1, None),
        (2, Decimal("99999999999999999999.25")),
        (3, Decimal("99999999999999999999.25")),
    ]
    assert runner.execute(
        "select k, first_value(v) over (order by v rows between "
        "unbounded preceding and current row) from wt order by k"
    ).rows == [
        (1, Decimal("-5.00")),
        (2, Decimal("-5.00")),
        (3, Decimal("-5.00")),
    ]


def test_holistic_aggs_over_long(runner):
    runner.execute("create table ht (k bigint, v decimal(38,2))")
    runner.execute(
        "insert into ht values (1, decimal '99999999999999999999.25'), "
        "(1, decimal '12345678901234567890.12'), (2, decimal '-5.00')"
    )
    assert runner.execute(
        "select min_by(v, k), max_by(v, k) from ht"
    ).rows == [
        (Decimal("99999999999999999999.25"), Decimal("-5.00"))
    ]
    got = runner.execute(
        "select approx_percentile(v, 0.5) from ht"
    ).rows[0][0]
    # global form goes through the quantile sketch: ~1.6% value resolution
    want = Decimal("12345678901234567890.12")
    assert abs(float(got - want)) / float(want) < 0.02
    # unsupported long paths fail loudly, never silently wrong
    import pytest as _pt

    with _pt.raises(Exception, match="long-decimal"):
        runner.execute("select array_agg(v) from ht")
    # window sum over long decimals runs the exact limb-plane path (the
    # tpcds q12 fix; see also tests/test_window.py)
    assert runner.execute(
        "select k, sum(v) over (partition by k) from ht order by k"
    ).rows == [
        (1, Decimal("112345678901234567889.37")),
        (1, Decimal("112345678901234567889.37")),
        (2, Decimal("-5.00")),
    ]


class TestSum128FastPath:
    """The provably-exact i64 fast path of _sum128 on the CPU fallback
    (segmented) path: when the input's declared precision bounds every
    partial sum inside i64, ONE i64 segment sum runs — statically, with no
    lax.cond and no runtime fits scan — for 1-D AND limb-plane (2-D)
    inputs (ROADMAP item 2's decimal(38) headline regression)."""

    def _sum(self, vals, gid, nseg, prec, two_d):
        import jax.numpy as jnp
        import numpy as np

        from trino_tpu.ops.aggregation import _sum128
        from trino_tpu.types.int128 import join_py, split_py

        if two_d:
            h = np.array([split_py(v)[0] for v in vals], np.int64)
            l = np.array([split_py(v)[1] for v in vals], np.int64)
            d = jnp.stack([jnp.asarray(h), jnp.asarray(l)], axis=-1)
        else:
            d = jnp.asarray(np.array(vals, np.int64))
        out = np.asarray(
            _sum128(d, jnp.asarray(np.array(gid)), nseg, None,
                    in_precision=prec)
        )
        return [join_py(int(out[s, 0]), int(out[s, 1])) for s in range(nseg)]

    @pytest.mark.parametrize("two_d", [False, True])
    def test_exact_at_the_boundary(self, two_d):
        vals = [10**12 - 1, -(10**12 - 1), 7, 10**12 - 1]
        gid = [0, 0, 1, 1]
        got = self._sum(vals, gid, 2, 12, two_d)
        assert got == [0, 10**12 + 6]

    def test_wide_values_still_exact(self):
        vals = [10**37, 10**37, -(10**36), 3]
        got = self._sum(vals, [0, 0, 1, 1], 2, 38, True)
        assert got == [2 * 10**37, 3 - 10**36]

    @pytest.mark.parametrize("two_d", [False, True])
    def test_provable_precision_compiles_no_cond(self, two_d):
        """The static proof removes the runtime branch entirely: the jaxpr
        of a provably-narrow sum contains NO cond primitive; an unprovable
        (wide) precision keeps the runtime-adaptive cond."""
        import jax
        import jax.numpy as jnp

        from trino_tpu.ops.aggregation import _sum128

        shape = (8, 2) if two_d else (8,)

        def jaxpr(prec):
            return str(
                jax.make_jaxpr(
                    lambda d, g: _sum128(d, g, 2, None, in_precision=prec)
                )(jnp.zeros(shape, jnp.int64), jnp.zeros(8, jnp.int64))
            )

        assert "cond" not in jaxpr(12)
        assert "cond" in jaxpr(38)

    def test_sum_of_narrow_decimal_widened_result(self, runner):
        """End to end: sum(decimal(12,2)) with a decimal(38) result — the
        common TPC-H shape the fast path exists for."""
        runner.execute("create table nr (k bigint, v decimal(12,2))")
        runner.execute(
            "insert into nr values (1, decimal '9999999999.99'), "
            "(1, decimal '0.01'), (2, decimal '-0.50'), (2, null)"
        )
        assert runner.execute(
            "select k, sum(v) from nr group by k order by k"
        ).rows == [(1, Decimal("10000000000.00")), (2, Decimal("-0.50"))]


class TestSumBoundLicense:
    """Boundary behavior of the range-certificate license (_sum128's
    sum_bound parameter, verify.numeric.sum_certificate): exact values at
    the 2**63-1 edges, mixed-sign cancellation, and limb-plane (2-D)
    inputs must all choose the proven path or correctly fall back."""

    def _sum(self, vals, gid, nseg, two_d, sum_bound):
        import jax.numpy as jnp
        import numpy as np

        from trino_tpu.ops.aggregation import _sum128
        from trino_tpu.types.int128 import join_py, split_py

        if two_d:
            h = np.array([split_py(v)[0] for v in vals], np.int64)
            l = np.array([split_py(v)[1] for v in vals], np.int64)
            d = jnp.stack([jnp.asarray(h), jnp.asarray(l)], axis=-1)
        else:
            d = jnp.asarray(np.array(vals, np.int64))
        out = np.asarray(
            _sum128(d, jnp.asarray(np.array(gid)), nseg, None,
                    sum_bound=sum_bound)
        )
        return [join_py(int(out[s, 0]), int(out[s, 1])) for s in range(nseg)]

    def _jaxpr(self, two_d, sum_bound):
        import jax
        import jax.numpy as jnp

        from trino_tpu.ops.aggregation import _sum128

        shape = (8, 2) if two_d else (8,)
        return str(
            jax.make_jaxpr(
                lambda d, g: _sum128(d, g, 2, None, sum_bound=sum_bound)
            )(jnp.zeros(shape, jnp.int64), jnp.zeros(8, jnp.int64))
        )

    @pytest.mark.parametrize("two_d", [False, True])
    def test_licensed_exact_at_i64_edge(self, two_d):
        """Values right at the proof bound: a certificate asserting the
        exact partial-sum bound keeps the single-plane path exact."""
        edge = (1 << 62) - 1
        vals = [edge, edge, -edge, 1]
        gid = [0, 1, 1, 1]
        # |any partial sum| <= edge (the true bound for these groups)
        got = self._sum(vals, gid, 2, two_d, sum_bound=edge)
        assert got == [edge, 1]

    @pytest.mark.parametrize("two_d", [False, True])
    def test_mixed_sign_cancellation(self, two_d):
        """Cancellation must be exact under the licensed path: partial
        sums visit both extremes before collapsing to a small result."""
        big = (1 << 61) + 12345
        vals = [big, -big, big, -big, 42]
        gid = [0] * 5
        got = self._sum(vals, gid, 1, two_d, sum_bound=(1 << 62))
        assert got == [42]

    @pytest.mark.parametrize("two_d", [False, True])
    def test_license_compiles_no_cond(self, two_d):
        """A licensed sum compiles with NO cond primitive (zero runtime
        fits checks); without a license the runtime probe survives."""
        assert "cond" not in self._jaxpr(two_d, sum_bound=10**12)
        assert "cond" in self._jaxpr(two_d, sum_bound=None)

    @pytest.mark.parametrize("two_d", [False, True])
    def test_bound_at_or_over_i64_falls_back(self, two_d):
        """sum_bound >= 2**63-1 proves nothing: the kernel must keep the
        runtime check and stay exact for sums ABOVE int64."""
        assert "cond" in self._jaxpr(two_d, sum_bound=(1 << 63) - 1)
        if two_d:
            over = (1 << 63) + 7  # needs the second limb
            got = self._sum([over // 2 + 1, over // 2, over - 1, 1],
                            [0, 0, 1, 1], 2, True, sum_bound=(1 << 70))
            assert got == [over, over]

    def test_certificate_refuses_unprovable(self):
        """sum_certificate licenses exactly when max_abs*rows < 2**63."""
        from trino_tpu.verify.ranges import Interval, certificate

        ok = certificate(Interval(-(10**10), 10**10), 2, 10**6)
        assert ok.licensed_i64_sum_bound() == 10**16
        edge = certificate(Interval(0, (1 << 62)), 2, 2)
        assert edge.licensed_i64_sum_bound() is None
        unbounded = certificate(Interval(None, 5), 2, 10)
        assert unbounded is None
