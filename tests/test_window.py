"""Window function tests vs pandas (reference style: TestWindowOperator +
AbstractTestWindowQueries)."""

import numpy as np
import pandas as pd
import pytest

from tests.test_e2e import assert_rows_match
from trino_tpu.runtime.runner import LocalQueryRunner
from trino_tpu.testing import tpch_pandas


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=2)


def test_row_number_rank(runner):
    n = tpch_pandas("tiny", "nation")
    df = n.sort_values(["n_regionkey", "n_name"])
    df = df.assign(
        rn=df.groupby("n_regionkey").cumcount() + 1,
    )
    expected = [
        (r.n_name, int(r.n_regionkey), int(r.rn)) for r in df.itertuples()
    ]
    res = runner.execute(
        "select n_name, n_regionkey, row_number() over "
        "(partition by n_regionkey order by n_name) rn from nation"
    )
    assert_rows_match(res.rows, expected, ordered=False)


def test_rank_with_ties(runner):
    res = runner.execute(
        "select x, rank() over (order by x), dense_rank() over (order by x) "
        "from (select 1 x union all select 1 union all select 2 union all select 3) t"
    )
    assert sorted(res.rows) == [(1, 1, 1), (1, 1, 1), (2, 3, 2), (3, 4, 3)]


def test_running_sum(runner):
    res = runner.execute(
        "select x, sum(x) over (order by x) from "
        "(select 1 x union all select 2 union all select 2 union all select 3) t"
    )
    # RANGE frame: peers share the running total
    assert sorted(res.rows) == [(1, 1), (2, 5), (2, 5), (3, 8)]


def test_partition_total(runner):
    o = tpch_pandas("tiny", "orders")
    per = o.groupby("o_custkey").o_orderkey.count()
    expected_pairs = {(int(k), int(v)) for k, v in per.items()}
    res = runner.execute(
        "select distinct o_custkey, count(*) over (partition by o_custkey) from orders"
    )
    assert set((int(a), int(b)) for a, b in res.rows) == expected_pairs


def test_lag_lead(runner):
    res = runner.execute(
        "select x, lag(x) over (order by x), lead(x, 1, 99) over (order by x) "
        "from (select 1 x union all select 2 union all select 3) t"
    )
    assert sorted(res.rows, key=lambda r: r[0]) == [
        (1, None, 2), (2, 1, 3), (3, 2, 99)
    ]


def test_ntile(runner):
    res = runner.execute(
        "select x, ntile(2) over (order by x) from "
        "(select 1 x union all select 2 union all select 3) t"
    )
    assert sorted(res.rows) == [(1, 1), (2, 1), (3, 2)]


def test_row_number_no_keys_filtered(runner):
    """row_number() over () on a filtered input must number only surviving
    rows 1..n (regression: dead rows were counted when no sort keys)."""
    res = runner.execute(
        "select n_name, row_number() over () rn from nation where n_regionkey = 2"
    )
    n = tpch_pandas("tiny", "nation")
    keep = set(n[n.n_regionkey == 2].n_name)
    names = {r[0] for r in res.rows}
    rns = sorted(r[1] for r in res.rows)
    assert names == keep
    assert rns == list(range(1, len(keep) + 1))


def test_rows_frame_running_sum_with_ties(runner):
    """ROWS frame is row-exact even under ties (RANGE would share totals)."""
    res = runner.execute(
        "select x, sum(x) over (order by x rows between unbounded preceding "
        "and current row) from "
        "(select 1 x union all select 2 union all select 2 union all select 3) t"
    )
    assert sorted(res.rows) == [(1, 1), (2, 3), (2, 5), (3, 8)]


def test_rows_frame_bounded_avg(runner):
    """avg over ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING (TPC-DS Q47 shape)."""
    res = runner.execute(
        "select x, avg(x) over (order by x rows between 1 preceding and 1 following) "
        "from (select 1 x union all select 2 union all select 4 union all select 8) t"
    )
    got = sorted((a, round(b, 6)) for a, b in res.rows)
    assert got == [(1, 1.5), (2, round(7 / 3, 6)), (4, round(14 / 3, 6)), (8, 6.0)]


def test_rows_frame_count_star_bounded(runner):
    res = runner.execute(
        "select x, count(*) over (order by x rows between 1 preceding and current row) "
        "from (select 1 x union all select 2 union all select 3) t"
    )
    assert sorted(res.rows) == [(1, 1), (2, 2), (3, 2)]


def test_last_value_rows_running(runner):
    """last_value with the row-exact running frame is the current row."""
    res = runner.execute(
        "select x, last_value(x) over (order by x rows between unbounded "
        "preceding and current row) from "
        "(select 1 x union all select 2 union all select 2) t"
    )
    assert sorted(res.rows) == [(1, 1), (2, 2), (2, 2)]


def test_unsupported_frame_raises(runner):
    from trino_tpu.planner.analyzer import AnalysisError

    with pytest.raises(AnalysisError):
        runner.execute(
            "select sum(x) over (order by x range between 1 preceding and "
            "current row) from (select 1 x) t"
        )


def test_frame_without_order_raises(runner):
    from trino_tpu.planner.analyzer import AnalysisError

    with pytest.raises(AnalysisError):
        runner.execute(
            "select sum(x) over (rows between unbounded preceding and "
            "current row) from (select 1 x) t"
        )


def test_avg_over_partition(runner):
    s = tpch_pandas("tiny", "supplier")
    expected = s.groupby("s_nationkey").s_acctbal.mean()
    res = runner.execute(
        "select distinct s_nationkey, avg(s_acctbal) over (partition by s_nationkey) "
        "from supplier"
    )
    got = {int(k): float(v) for k, v in res.rows}
    for k, v in expected.items():
        # window avg over decimal rounds to the decimal scale
        assert abs(got[int(k)] - float(v)) < 0.0051


@pytest.mark.smoke
def test_bounded_rows_frame_min_max(runner):
    """Sliding min/max over a bounded-start ROWS frame (sparse-table range
    query kernel; the round-3 engine rejected these at analysis)."""
    rows = runner.execute(
        "select n_nationkey, "
        "min(n_nationkey) over (partition by n_regionkey order by n_nationkey "
        "  rows between 2 preceding and 1 following), "
        "max(n_nationkey) over (partition by n_regionkey order by n_nationkey "
        "  rows between 1 preceding and current row) "
        "from nation order by n_regionkey, n_nationkey"
    ).rows
    import collections

    by_region = collections.defaultdict(list)
    base = runner.execute(
        "select n_regionkey, n_nationkey from nation "
        "order by n_regionkey, n_nationkey"
    ).rows
    for rk, nk in base:
        by_region[rk].append(nk)
    expect = {}
    for rk, vals in by_region.items():
        for i, v in enumerate(vals):
            lo = max(0, i - 2)
            hi = min(len(vals) - 1, i + 1)
            expect[v] = (min(vals[lo:hi + 1]), max(vals[max(0, i - 1):i + 1]))
    for nk, got_min, got_max in rows:
        assert (got_min, got_max) == expect[nk], nk


def test_named_window_clause(runner):
    rows = runner.execute(
        "select n_name, rank() over w, "
        "sum(n_nationkey) over (w rows between 1 preceding and current row) "
        "from nation where n_regionkey = 1 "
        "window w as (partition by n_regionkey order by n_nationkey) "
        "order by n_nationkey"
    ).rows
    assert rows[0] == ("ARGENTINA", 1, 1)
    assert rows[1] == ("BRAZIL", 2, 3)


def test_named_window_inheritance_chain(runner):
    rows = runner.execute(
        "select n_name, row_number() over w2 from nation where n_regionkey=2 "
        "window w as (partition by n_regionkey), "
        "w2 as (w order by n_name desc) order by n_name limit 2"
    ).rows
    assert rows == [("CHINA", 5), ("INDIA", 4)]


def test_named_window_undefined(runner):
    import pytest

    with pytest.raises(Exception, match="window 'wz' is not defined"):
        runner.execute("select rank() over wz from nation")


def test_ignore_nulls_navigation(runner):
    runner.execute("drop table if exists memory.default.ign")
    runner.execute(
        "create table memory.default.ign as select * from (values "
        "(1, 10), (2, null), (3, null), (4, 40), (5, null)) t(i, x)"
    )
    rows = runner.execute(
        "select i, lag(x) ignore nulls over (order by i), "
        "lead(x) ignore nulls over (order by i), "
        "first_value(x) ignore nulls over (order by i), "
        "last_value(x) ignore nulls over (order by i) "
        "from memory.default.ign order by i"
    ).rows
    assert rows == [
        (1, None, 40, 10, 10),
        (2, 10, 40, 10, 10),
        (3, 10, 40, 10, 10),
        (4, 10, None, 10, 40),
        (5, 40, None, 10, 40),
    ]


def test_ignore_nulls_lag_offset_and_partition(runner):
    runner.execute("drop table if exists memory.default.ign2")
    runner.execute(
        "create table memory.default.ign2 as select * from (values "
        "(1, 1, 'a'), (1, 2, null), (1, 3, 'c'), (1, 4, null), (1, 5, null), "
        "(2, 1, null), (2, 2, 'z')) t(g, i, x)"
    )
    rows = runner.execute(
        "select g, i, lag(x, 2) ignore nulls over (partition by g order by i) "
        "from memory.default.ign2 order by g, i"
    ).rows
    assert rows == [
        (1, 1, None), (1, 2, None), (1, 3, None), (1, 4, "a"), (1, 5, "a"),
        (2, 1, None), (2, 2, None),
    ]


def test_ignore_nulls_respect_default(runner):
    runner.execute("drop table if exists memory.default.ignr")
    runner.execute(
        "create table memory.default.ignr as select * from (values "
        "(1, 10), (2, null), (3, null), (4, 40), (5, null)) t(i, x)"
    )
    rows = runner.execute(
        "select i, lag(x) respect nulls over (order by i) "
        "from memory.default.ignr order by i"
    ).rows
    assert rows == [(1, None), (2, 10), (3, None), (4, None), (5, 40)]


def test_ignore_nulls_invalid_function(runner):
    import pytest

    with pytest.raises(Exception, match="IGNORE NULLS is not valid"):
        runner.execute(
            "select rank() ignore nulls over (order by n_nationkey) from nation"
        )


def test_ignore_nulls_distributed(runner):
    from trino_tpu.parallel.runner import DistributedQueryRunner

    d = DistributedQueryRunner(catalog="tpch", schema="tiny")
    sql = (
        "select l_orderkey, l_linenumber, lag(l_comment) ignore nulls "
        "over (partition by l_returnflag order by l_orderkey, l_linenumber) "
        "from lineitem order by 1, 2 limit 20"
    )
    assert d.execute(sql).rows == runner.execute(sql).rows


def test_null_treatment_requires_over(runner):
    import pytest

    with pytest.raises(Exception, match="requires an OVER clause"):
        runner.execute("select max(n_nationkey) ignore nulls from nation")


def test_duplicate_window_name_rejected(runner):
    import pytest

    with pytest.raises(Exception, match="specified more than once"):
        runner.execute(
            "select rank() over w from nation "
            "window w as (order by n_name), w as (order by n_regionkey)"
        )


def test_nth_value(runner):
    rows = runner.execute(
        "select n_nationkey, nth_value(n_name, 2) over "
        "(partition by n_regionkey order by n_nationkey "
        "rows between unbounded preceding and unbounded following) "
        "from nation where n_regionkey = 1 order by n_nationkey"
    ).rows
    assert all(v == "BRAZIL" for _, v in rows)
    # running frame: n-th row beyond the frame end is NULL
    rows = runner.execute(
        "select x, nth_value(x, 2) over (order by x) from "
        "(select 1 x union all select 2 union all select 3) t"
    ).rows
    assert sorted(rows) == [(1, None), (2, 2), (3, 2)]


def test_nth_value_ignore_nulls(runner):
    runner.execute("drop table if exists memory.default.ignn")
    runner.execute(
        "create table memory.default.ignn as select * from (values "
        "(1, 10), (2, null), (3, null), (4, 40), (5, null)) t(i, x)"
    )
    rows = runner.execute(
        "select i, nth_value(x, 2) ignore nulls over "
        "(order by i rows between unbounded preceding and unbounded following) "
        "from memory.default.ignn order by i"
    ).rows
    assert [v for _, v in rows] == [40] * 5


def test_nth_value_validation(runner):
    with pytest.raises(Exception, match="nth_value"):
        runner.execute(
            "select nth_value(n_name) over (order by n_nationkey) from nation"
        )
    with pytest.raises(Exception, match="positive"):
        runner.execute(
            "select nth_value(n_name, 0) over (order by n_nationkey) from nation"
        )


# -- long-decimal (Int128) window sum/avg — the tpcds q12 shape ---------------


def test_window_sum_over_long_decimal(runner):
    """sum() over a decimal(38,s) limb-plane input column: the tpcds q12
    regression (window-over-aggregate widens the input to Int128)."""
    res = runner.execute(
        "select l_returnflag, s, sum(s) over (partition by l_returnflag) "
        "from (select l_returnflag, l_linestatus, "
        "      sum(l_extendedprice) s from lineitem "
        "      group by l_returnflag, l_linestatus) t"
    )
    li = tpch_pandas("tiny", "lineitem")
    inner = li.groupby(["l_returnflag", "l_linestatus"]).l_extendedprice.sum()
    outer = inner.groupby(level=0).sum()
    got = {
        (flag, str(s), str(tot)) for flag, s, tot in res.rows
    }
    expected = {
        (flag, f"{inner[(flag, ls)]:.2f}", f"{outer[flag]:.2f}")
        for flag, ls in inner.index
    }
    assert got == expected


def test_window_running_sum_long_decimal(runner):
    """Running (ORDER BY) frame over limb planes: exact prefix-sum path."""
    res = runner.execute(
        "select l_linestatus, sum(s) over (order by l_linestatus) "
        "from (select l_linestatus, sum(l_extendedprice) s "
        "      from lineitem group by l_linestatus) t"
    )
    li = tpch_pandas("tiny", "lineitem")
    inner = li.groupby("l_linestatus").l_extendedprice.sum().sort_index()
    running = inner.cumsum()
    got = {(ls, str(v)) for ls, v in res.rows}
    expected = {(ls, f"{running[ls]:.2f}") for ls in inner.index}
    assert got == expected


def test_window_avg_long_decimal(runner):
    """avg() over limb planes: exact Int128 divide, round half away."""
    res = runner.execute(
        "select l_returnflag, avg(s) over (partition by l_returnflag) "
        "from (select l_returnflag, l_linestatus, "
        "      sum(l_extendedprice) s from lineitem "
        "      group by l_returnflag, l_linestatus) t"
    )
    from decimal import ROUND_HALF_UP, Decimal

    li = tpch_pandas("tiny", "lineitem")
    inner = li.groupby(["l_returnflag", "l_linestatus"]).l_extendedprice.sum()
    got = {(flag, str(v)) for flag, v in res.rows}
    expected = set()
    for flag in inner.index.get_level_values(0).unique():
        grp = inner[flag]
        cents = [int(round(x * 100)) for x in grp]
        avg = (Decimal(sum(cents)) / len(cents)).quantize(
            Decimal(1), rounding=ROUND_HALF_UP
        )
        expected.add((flag, f"{Decimal(avg) / 100:.2f}"))
    assert got == expected


def test_window_long_decimal_null_inputs(runner):
    """Validity threads through the limb-plane frame sums: NULL inputs
    do not contribute, all-NULL partitions yield NULL (not zero)."""
    runner.execute("drop table if exists memory.default.wld")
    runner.execute(
        "create table memory.default.wld as select * from (values "
        "(1, cast(10.50 as decimal(38,2))), "
        "(1, cast(null as decimal(38,2))), "
        "(1, cast(2.25 as decimal(38,2))), "
        "(2, cast(null as decimal(38,2))), "
        "(2, cast(null as decimal(38,2)))) t(k, x)"
    )
    rows = runner.execute(
        "select k, sum(x) over (partition by k), "
        "avg(x) over (partition by k) from memory.default.wld"
    ).rows
    by_k = {}
    for k, s, a in rows:
        by_k[k] = (None if s is None else str(s), None if a is None else str(a))
    assert by_k[1] == ("12.75", "6.38")  # 12.75/2 = 6.375 -> half away
    assert by_k[2] == (None, None)
