"""Window function tests vs pandas (reference style: TestWindowOperator +
AbstractTestWindowQueries)."""

import numpy as np
import pandas as pd
import pytest

from tests.test_e2e import assert_rows_match
from trino_tpu.runtime.runner import LocalQueryRunner
from trino_tpu.testing import tpch_pandas


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=2)


def test_row_number_rank(runner):
    n = tpch_pandas("tiny", "nation")
    df = n.sort_values(["n_regionkey", "n_name"])
    df = df.assign(
        rn=df.groupby("n_regionkey").cumcount() + 1,
    )
    expected = [
        (r.n_name, int(r.n_regionkey), int(r.rn)) for r in df.itertuples()
    ]
    res = runner.execute(
        "select n_name, n_regionkey, row_number() over "
        "(partition by n_regionkey order by n_name) rn from nation"
    )
    assert_rows_match(res.rows, expected, ordered=False)


def test_rank_with_ties(runner):
    res = runner.execute(
        "select x, rank() over (order by x), dense_rank() over (order by x) "
        "from (select 1 x union all select 1 union all select 2 union all select 3) t"
    )
    assert sorted(res.rows) == [(1, 1, 1), (1, 1, 1), (2, 3, 2), (3, 4, 3)]


def test_running_sum(runner):
    res = runner.execute(
        "select x, sum(x) over (order by x) from "
        "(select 1 x union all select 2 union all select 2 union all select 3) t"
    )
    # RANGE frame: peers share the running total
    assert sorted(res.rows) == [(1, 1), (2, 5), (2, 5), (3, 8)]


def test_partition_total(runner):
    o = tpch_pandas("tiny", "orders")
    per = o.groupby("o_custkey").o_orderkey.count()
    expected_pairs = {(int(k), int(v)) for k, v in per.items()}
    res = runner.execute(
        "select distinct o_custkey, count(*) over (partition by o_custkey) from orders"
    )
    assert set((int(a), int(b)) for a, b in res.rows) == expected_pairs


def test_lag_lead(runner):
    res = runner.execute(
        "select x, lag(x) over (order by x), lead(x, 1, 99) over (order by x) "
        "from (select 1 x union all select 2 union all select 3) t"
    )
    assert sorted(res.rows, key=lambda r: r[0]) == [
        (1, None, 2), (2, 1, 3), (3, 2, 99)
    ]


def test_ntile(runner):
    res = runner.execute(
        "select x, ntile(2) over (order by x) from "
        "(select 1 x union all select 2 union all select 3) t"
    )
    assert sorted(res.rows) == [(1, 1), (2, 1), (3, 2)]


def test_row_number_no_keys_filtered(runner):
    """row_number() over () on a filtered input must number only surviving
    rows 1..n (regression: dead rows were counted when no sort keys)."""
    res = runner.execute(
        "select n_name, row_number() over () rn from nation where n_regionkey = 2"
    )
    n = tpch_pandas("tiny", "nation")
    keep = set(n[n.n_regionkey == 2].n_name)
    names = {r[0] for r in res.rows}
    rns = sorted(r[1] for r in res.rows)
    assert names == keep
    assert rns == list(range(1, len(keep) + 1))


def test_rows_frame_running_sum_with_ties(runner):
    """ROWS frame is row-exact even under ties (RANGE would share totals)."""
    res = runner.execute(
        "select x, sum(x) over (order by x rows between unbounded preceding "
        "and current row) from "
        "(select 1 x union all select 2 union all select 2 union all select 3) t"
    )
    assert sorted(res.rows) == [(1, 1), (2, 3), (2, 5), (3, 8)]


def test_rows_frame_bounded_avg(runner):
    """avg over ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING (TPC-DS Q47 shape)."""
    res = runner.execute(
        "select x, avg(x) over (order by x rows between 1 preceding and 1 following) "
        "from (select 1 x union all select 2 union all select 4 union all select 8) t"
    )
    got = sorted((a, round(b, 6)) for a, b in res.rows)
    assert got == [(1, 1.5), (2, round(7 / 3, 6)), (4, round(14 / 3, 6)), (8, 6.0)]


def test_rows_frame_count_star_bounded(runner):
    res = runner.execute(
        "select x, count(*) over (order by x rows between 1 preceding and current row) "
        "from (select 1 x union all select 2 union all select 3) t"
    )
    assert sorted(res.rows) == [(1, 1), (2, 2), (3, 2)]


def test_last_value_rows_running(runner):
    """last_value with the row-exact running frame is the current row."""
    res = runner.execute(
        "select x, last_value(x) over (order by x rows between unbounded "
        "preceding and current row) from "
        "(select 1 x union all select 2 union all select 2) t"
    )
    assert sorted(res.rows) == [(1, 1), (2, 2), (2, 2)]


def test_unsupported_frame_raises(runner):
    from trino_tpu.planner.analyzer import AnalysisError

    with pytest.raises(AnalysisError):
        runner.execute(
            "select sum(x) over (order by x range between 1 preceding and "
            "current row) from (select 1 x) t"
        )


def test_frame_without_order_raises(runner):
    from trino_tpu.planner.analyzer import AnalysisError

    with pytest.raises(AnalysisError):
        runner.execute(
            "select sum(x) over (rows between unbounded preceding and "
            "current row) from (select 1 x) t"
        )


def test_avg_over_partition(runner):
    s = tpch_pandas("tiny", "supplier")
    expected = s.groupby("s_nationkey").s_acctbal.mean()
    res = runner.execute(
        "select distinct s_nationkey, avg(s_acctbal) over (partition by s_nationkey) "
        "from supplier"
    )
    got = {int(k): float(v) for k, v in res.rows}
    for k, v in expected.items():
        # window avg over decimal rounds to the decimal scale
        assert abs(got[int(k)] - float(v)) < 0.0051


@pytest.mark.smoke
def test_bounded_rows_frame_min_max(runner):
    """Sliding min/max over a bounded-start ROWS frame (sparse-table range
    query kernel; the round-3 engine rejected these at analysis)."""
    rows = runner.execute(
        "select n_nationkey, "
        "min(n_nationkey) over (partition by n_regionkey order by n_nationkey "
        "  rows between 2 preceding and 1 following), "
        "max(n_nationkey) over (partition by n_regionkey order by n_nationkey "
        "  rows between 1 preceding and current row) "
        "from nation order by n_regionkey, n_nationkey"
    ).rows
    import collections

    by_region = collections.defaultdict(list)
    base = runner.execute(
        "select n_regionkey, n_nationkey from nation "
        "order by n_regionkey, n_nationkey"
    ).rows
    for rk, nk in base:
        by_region[rk].append(nk)
    expect = {}
    for rk, vals in by_region.items():
        for i, v in enumerate(vals):
            lo = max(0, i - 2)
            hi = min(len(vals) - 1, i + 1)
            expect[v] = (min(vals[lo:hi + 1]), max(vals[max(0, i - 1):i + 1]))
    for nk, got_min, got_max in rows:
        assert (got_min, got_max) == expect[nk], nk
