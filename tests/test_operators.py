"""Operator tests against the pandas oracle (reference style:
operator/TestHashAggregationOperator.java etc. with RowPagesBuilder input)."""

import datetime
from decimal import Decimal

import numpy as np
import pandas as pd
import pytest

pytestmark = pytest.mark.smoke

from trino_tpu import types as T
from trino_tpu.columnar import batch_from_rows
from trino_tpu.connectors.api import TableHandle
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.expr import InputRef, Literal, Call
from trino_tpu.expr.ir import and_, comparison
from trino_tpu.ops.aggregation import AggregationOperator, AggSpec
from trino_tpu.ops.filter_project import FilterProjectOperator
from trino_tpu.ops.scan import ScanOperator
from trino_tpu.ops.sort import LimitOperator, OrderByOperator, TopNOperator
from trino_tpu.ops.common import SortKey
from trino_tpu.runtime.driver import Driver
from trino_tpu.testing import tpch_pandas

DEC = T.DecimalType(12, 2)


def _batches(types, rows, chunk=3):
    """Yield device batches in chunks (tests multi-batch streaming)."""
    out = []
    for i in range(0, len(rows), chunk):
        out.append(batch_from_rows(types, rows[i : i + chunk]).device_put())
    return out


def test_grouped_agg_vs_pandas():
    rows = [
        ["a", 1, 10.0], ["b", 2, None], ["a", 3, 30.0], ["c", None, 5.0],
        ["b", 5, 50.0], ["a", None, None], ["c", 7, 70.0], ["a", 8, 80.0],
    ]
    types = [T.VARCHAR, T.BIGINT, T.DOUBLE]
    op = AggregationOperator(
        [0],
        [
            AggSpec("count_star", None, T.BIGINT),
            AggSpec("sum", 1, T.BIGINT),
            AggSpec("avg", 2, T.DOUBLE),
            AggSpec("min", 1, T.BIGINT),
            AggSpec("max", 2, T.DOUBLE),
            AggSpec("count", 1, T.BIGINT),
        ],
        types,
    )
    got = Driver(_batches(types, rows), [op]).rows()
    got.sort(key=lambda r: r[0])
    df = pd.DataFrame(rows, columns=["k", "x", "y"])
    exp = (
        df.groupby("k")
        .agg(
            n=("k", "size"), sx=("x", "sum"), ay=("y", "mean"),
            mn=("x", "min"), mx=("y", "max"), cx=("x", "count"),
        )
        .reset_index()
        .sort_values("k")
    )
    for g, e in zip(got, exp.itertuples(index=False)):
        assert g[0] == e.k and g[1] == e.n
        assert g[2] == (None if pd.isna(e.sx) else int(e.sx))
        assert g[3] == pytest.approx(e.ay) if not pd.isna(e.ay) else g[3] is None
        assert g[4] == (None if pd.isna(e.mn) else int(e.mn))
        assert g[5] == (pytest.approx(e.mx) if not pd.isna(e.mx) else None)
        assert g[6] == e.cx


def test_streaming_agg_matches_materialized():
    rows = [[i % 4, i] for i in range(50)]
    types = [T.BIGINT, T.BIGINT]
    aggs = [AggSpec("sum", 1, T.BIGINT), AggSpec("avg", 1, T.DOUBLE),
            AggSpec("count_star", None, T.BIGINT)]
    a = Driver(_batches(types, rows, chunk=7),
               [AggregationOperator([0], aggs, types, streaming=True)]).rows()
    b = Driver(_batches(types, rows, chunk=7),
               [AggregationOperator([0], aggs, types, streaming=False)]).rows()
    assert sorted(a) == sorted(b)


def test_global_agg_empty_input():
    types = [T.BIGINT]
    op = AggregationOperator([], [AggSpec("count_star", None, T.BIGINT),
                                  AggSpec("sum", 0, T.BIGINT)], types)
    got = Driver(iter(()), [op]).rows()
    assert got == [[0, None]]


def test_partial_final_roundtrip():
    rows = [[i % 3, i * 10] for i in range(30)]
    types = [T.BIGINT, T.BIGINT]
    aggs = [AggSpec("avg", 1, T.DOUBLE), AggSpec("count", 1, T.BIGINT)]
    partial = AggregationOperator([0], aggs, types, mode="partial")
    pbatches = list(Driver(_batches(types, rows, chunk=9), [partial]).run())
    state_types = [c.type for c in pbatches[0].columns]
    # final agg over states: args point at state channel offsets
    final = AggregationOperator(
        [0],
        [AggSpec("avg", 1, T.DOUBLE), AggSpec("count", 3, T.BIGINT)],
        state_types,
        mode="final",
    )
    got = Driver(iter(pbatches), [final]).rows()
    single = Driver(
        _batches(types, rows, chunk=9), [AggregationOperator([0], aggs, types)]
    ).rows()
    assert sorted(got) == sorted(single)


def test_orderby_topn_limit():
    rows = [[i, (i * 37) % 11, None if i % 5 == 0 else i % 3] for i in range(20)]
    types = [T.BIGINT, T.BIGINT, T.BIGINT]
    keys = [SortKey(2, ascending=True), SortKey(1, ascending=False)]
    got = Driver(_batches(types, rows, chunk=6), [OrderByOperator(keys)]).rows()
    df = pd.DataFrame(rows, columns=["i", "a", "b"])
    exp = df.sort_values(["b", "a"], ascending=[True, False],
                         na_position="last", kind="stable")
    assert [r[0] for r in got] == exp["i"].tolist()
    # TopN == first 5 of full sort
    topn = Driver(_batches(types, rows, chunk=6), [TopNOperator(keys, 5)]).rows()
    assert [r[0] for r in topn] == exp["i"].tolist()[:5]
    # limit
    lim = Driver(_batches(types, rows, chunk=6), [LimitOperator(7)]).rows()
    assert len(lim) == 7 and [r[0] for r in lim] == [r[0] for r in rows[:7]]


def test_scan_filter_agg_q6_tiny():
    """TPC-H Q6 as a hand-built pipeline (reference: HandTpchQuery6.java)."""
    conn = TpchConnector()
    h = TableHandle("tpch", "tiny", "lineitem")
    meta = conn.metadata().table_metadata("tiny", "lineitem")
    cols = ["l_shipdate", "l_discount", "l_quantity", "l_extendedprice"]
    types = [meta.column(c).type for c in cols]
    d0 = (datetime.date(1994, 1, 1) - datetime.date(1970, 1, 1)).days
    d1 = (datetime.date(1995, 1, 1) - datetime.date(1970, 1, 1)).days
    ship, disc, qty, price = (InputRef(i, t) for i, t in enumerate(types))
    pred = and_(
        comparison(">=", ship, Literal(d0, T.DATE)),
        comparison("<", ship, Literal(d1, T.DATE)),
        comparison(">=", disc, Literal(Decimal("0.05"), DEC)),
        comparison("<=", disc, Literal(Decimal("0.07"), DEC)),
        comparison("<", qty, Literal(24, DEC)),
    )
    proj = [Call("$mul", [price, disc], T.DecimalType(18, 4))]

    def source():
        for split in conn.splits(h, target_splits=3):
            yield from ScanOperator(conn, split, cols, types).batches()

    ops = [
        FilterProjectOperator(pred, proj),
        AggregationOperator([], [AggSpec("sum", 0, T.DecimalType(18, 4))],
                            [T.DecimalType(18, 4)], streaming=True),
    ]
    got = Driver(source(), ops).rows()

    li = tpch_pandas("tiny", "lineitem")
    m = (
        (li["l_shipdate"].values.astype("datetime64[D]")
         >= np.datetime64("1994-01-01"))
        & (li["l_shipdate"].values.astype("datetime64[D]")
           < np.datetime64("1995-01-01"))
        & (li["l_discount__cents"] >= 5) & (li["l_discount__cents"] <= 7)
        & (li["l_quantity__cents"] < 2400)
    )
    exp_units = int((li["l_extendedprice__cents"][m] * li["l_discount__cents"][m]).sum())
    assert got[0][0] == Decimal(exp_units).scaleb(-4)


def test_desc_sort_int64_min_and_nan():
    rows = [[-(2**63), 1.5], [0, float("nan")], [5, -2.0]]
    types = [T.BIGINT, T.DOUBLE]
    got = Driver(_batches(types, rows, chunk=3),
                 [OrderByOperator([SortKey(0, ascending=False)])]).rows()
    assert [r[0] for r in got] == [5, 0, -(2**63)]
    # NaN sorts largest: first under DESC, last under ASC
    got = Driver(_batches(types, rows, chunk=3),
                 [OrderByOperator([SortKey(1, ascending=False)])]).rows()
    assert np.isnan(got[0][1])
    got = Driver(_batches(types, rows, chunk=3),
                 [OrderByOperator([SortKey(1, ascending=True)])]).rows()
    assert np.isnan(got[-1][1])


def test_integer_sum_widens():
    rows = [[0, 2_000_000_000], [0, 2_000_000_000]]
    types = [T.BIGINT, T.INTEGER]
    got = Driver(_batches(types, rows),
                 [AggregationOperator([0], [AggSpec("sum", 1, T.BIGINT)], types)]).rows()
    assert got == [[0, 4_000_000_000]]


def test_any_value_skips_nulls():
    rows = [["a", None], ["a", 42], ["b", 7]]
    types = [T.VARCHAR, T.BIGINT]
    got = Driver(_batches(types, rows, chunk=3),
                 [AggregationOperator([0], [AggSpec("any_value", 1, T.BIGINT)], types)]).rows()
    assert sorted(got) == [["a", 42], ["b", 7]]


def test_streaming_folds_state():
    rows = [[i % 3, i] for i in range(100)]
    types = [T.BIGINT, T.BIGINT]
    op = AggregationOperator([0], [AggSpec("sum", 1, T.BIGINT)], types, streaming=True)
    got = Driver(_batches(types, rows, chunk=5), [op]).rows()  # 20 batches > FOLD_EVERY
    df = pd.DataFrame(rows, columns=["k", "x"]).groupby("k")["x"].sum()
    assert sorted(got) == [[k, int(v)] for k, v in df.items()]
