"""Bivariate aggregate tests vs numpy (reference: operator/aggregation/
CovarianceAggregation, CorrelationAggregation, regr_* family)."""

import numpy as np
import pytest

pytestmark = pytest.mark.smoke


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime.runner import LocalQueryRunner

    r = LocalQueryRunner(catalog="memory", schema="default", target_splits=2)
    rng = np.random.default_rng(7)
    xs = rng.normal(size=40)
    ys = 2.5 * xs + 1.0 + rng.normal(scale=0.1, size=40)
    vals = ", ".join(
        f"({1 + i % 2}, {round(float(y), 12)}, {round(float(x), 12)})"
        for i, (x, y) in enumerate(zip(xs, ys))
    )
    r.execute("create table pts (g bigint, y double, x double)")
    r.execute(f"insert into pts values {vals}")
    r._xy = (
        np.array([round(float(y), 12) for y in ys]),
        np.array([round(float(x), 12) for x in xs]),
    )
    return r


def test_corr_covar_match_numpy(runner):
    y, x = runner._xy
    got = runner.execute(
        "select corr(y, x), covar_samp(y, x), covar_pop(y, x) from pts"
    ).rows[0]
    assert got[0] == pytest.approx(np.corrcoef(y, x)[0, 1], abs=1e-9)
    assert got[1] == pytest.approx(np.cov(y, x, ddof=1)[0, 1], abs=1e-9)
    assert got[2] == pytest.approx(np.cov(y, x, ddof=0)[0, 1], abs=1e-9)


def test_regression_match_polyfit(runner):
    y, x = runner._xy
    slope, intercept = np.polyfit(x, y, 1)
    got = runner.execute(
        "select regr_slope(y, x), regr_intercept(y, x) from pts"
    ).rows[0]
    assert got[0] == pytest.approx(slope, abs=1e-9)
    assert got[1] == pytest.approx(intercept, abs=1e-9)


def test_grouped(runner):
    rows = runner.execute(
        "select g, corr(y, x) from pts group by g order by g"
    ).rows
    assert len(rows) == 2
    for _, c in rows:
        assert 0.99 < c <= 1.0


def test_pairwise_null_skip(runner):
    rows = runner.execute(
        "select covar_pop(y, x), corr(y, x) from "
        "(values (1.0, 2.0), (null, 5.0), (3.0, null), (3.0, 4.0)) "
        "as t(y, x)"
    ).rows
    # only (1,2) and (3,4) count: covar_pop = 7 - 2*3 = 1, corr = 1
    assert rows[0][0] == pytest.approx(1.0)
    assert rows[0][1] == pytest.approx(1.0)


def test_degenerate_null(runner):
    rows = runner.execute(
        "select corr(y, x), regr_slope(y, x) from "
        "(values (1.0, 2.0)) as t(y, x)"
    ).rows
    assert rows == [(None, None)]  # n <= 1: undefined


def test_distributed_matches_local(runner):
    from trino_tpu.parallel.runner import DistributedQueryRunner

    sql = (
        "select l_returnflag, round(corr(l_extendedprice, l_quantity), 6) "
        "from lineitem group by l_returnflag order by 1"
    )
    from trino_tpu.runtime.runner import LocalQueryRunner

    a = LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=3).execute(sql).rows
    b = DistributedQueryRunner(catalog="tpch", schema="tiny").execute(sql).rows
    assert a == b
