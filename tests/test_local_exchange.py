"""Intra-task parallelism tests: local exchange split readers + scaled
writers (reference: operator/exchange/LocalExchange.java, task_concurrency,
scaled writer operators)."""

import threading

import pytest

pytestmark = pytest.mark.smoke


def test_parallel_feed_yields_everything():
    from trino_tpu.runtime.local_exchange import parallel_feed

    makers = [lambda k=k: iter(range(k * 10, k * 10 + 5)) for k in range(6)]
    got = sorted(parallel_feed(makers, workers=3))
    assert got == sorted(x for k in range(6) for x in range(k * 10, k * 10 + 5))


def test_parallel_feed_uses_threads():
    from trino_tpu.runtime.local_exchange import parallel_feed

    seen = set()
    gate = threading.Barrier(2, timeout=10)

    def maker(k):
        def gen():
            seen.add(threading.current_thread().name)
            gate.wait()  # forces two producers to be live simultaneously
            yield k

        return gen

    list(parallel_feed([maker(k) for k in range(2)], workers=2))
    assert len(seen) == 2  # two producer threads ran concurrently


def test_parallel_feed_propagates_errors():
    from trino_tpu.runtime.local_exchange import parallel_feed

    def boom():
        raise RuntimeError("reader died")
        yield  # pragma: no cover

    with pytest.raises(RuntimeError, match="reader died"):
        list(parallel_feed([boom, boom], workers=2))


def test_scan_results_identical_under_concurrency():
    from trino_tpu.runtime.runner import LocalQueryRunner

    r = LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=6)
    q = (
        "select l_returnflag, count(*), sum(l_quantity) from lineitem "
        "group by l_returnflag order by l_returnflag"
    )
    r.properties.set("task_concurrency", 1)
    serial = r.execute(q).rows
    r.properties.set("task_concurrency", 4)
    parallel = r.execute(q).rows
    assert serial == parallel


def test_scaled_writers_roundtrip():
    from trino_tpu.runtime.runner import LocalQueryRunner

    r = LocalQueryRunner(catalog="memory", schema="default", target_splits=2)
    r.properties.set("writer_count", 4)
    r.execute("create table w (a bigint, b varchar, c double)")
    values = ", ".join(f"({i}, 'v{i % 7}', {i}.5)" for i in range(2000))
    r.execute(f"insert into w values {values}")
    assert r.execute("select count(*), sum(a) from w").rows == [
        (2000, sum(range(2000)))
    ]
    assert r.execute(
        "select count(distinct b) from w"
    ).rows == [(7,)]
