"""Query performance observatory tests: the persistent per-query profile
archive (telemetry/profile_store), device-gate contention telemetry
(runtime/dispatcher device_slice), differential drift attribution
(tools/profile_diff + the compare_bench check_drift gate), the JSONL
audit log (telemetry/audit), and the lane-safety contract for
last_mesh_profile / last_trace under concurrent engine lanes."""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tool(name):
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


from trino_tpu.runtime.runner import LocalQueryRunner
from trino_tpu.telemetry import REGISTRY
from trino_tpu.telemetry.profile_store import (
    ARTIFACT_PHASES,
    ProfileStore,
    attach_profile_store,
    build_artifact,
    sql_hash,
)


@pytest.fixture(scope="module")
def dist():
    from trino_tpu.parallel import DistributedQueryRunner

    return DistributedQueryRunner(n_workers=8, schema="tiny")


def _artifact(query_id="query_1", sql="select 1", wall=1.0, phases=None,
              fragments=(), gate_wait=0.0, counters=None, coll=None):
    """Hand-built artifact with chosen phase values (via a stub profile)."""

    class _Prof:
        def to_json(self):
            return {
                "fragments": list(fragments),
                "counters": dict(counters or {}),
                "trace_cache": {"hits": 0, "misses": 0, "retraces": 0},
                "collective_bytes_by": dict(coll or {}),
            }

        def phase_totals(self):
            return dict(phases or {})

    return build_artifact(
        query_id=query_id, sql=sql, state="FINISHED", wall_s=wall,
        mesh_profile=_Prof() if phases is not None or fragments else None,
        gate_wait_s=gate_wait,
    )


# -- artifact assembly ---------------------------------------------------------


class TestArtifact:
    def test_phases_sum_to_wall_exactly(self):
        art = _artifact(
            wall=2.5,
            phases={"trace": 0.5, "compute": 1.0, "transfer": 0.25},
            gate_wait=0.125,
        )
        assert abs(sum(art["phases"].values()) - art["wall_s"]) < 1e-12
        assert art["phases"]["gate_wait"] == 0.125
        # the remainder is NAMED, not dropped
        assert art["phases"]["unattributed"] == pytest.approx(0.625)

    def test_unattributed_can_go_negative_but_still_sums(self):
        # overlapping measurements can exceed wall; the invariant is the
        # SUM, and a negative remainder is a visible fact, not a lie
        art = _artifact(wall=1.0, phases={"compute": 1.5})
        assert art["phases"]["unattributed"] == pytest.approx(-0.5)
        assert abs(sum(art["phases"].values()) - art["wall_s"]) < 1e-12

    def test_artifact_key_and_hash(self):
        a = _artifact(sql="select  1")
        b = _artifact(query_id="query_2", sql="SELECT 1")
        assert a["sql_hash"] == b["sql_hash"]  # normalized
        assert a["key"] != b["key"]  # query id in the key
        assert a["version"] == 1

    def test_local_artifact_has_empty_mesh_sections(self):
        art = _artifact()
        assert art["fragments"] == []
        assert art["mesh"] == "local"
        assert art["phases"]["unattributed"] == pytest.approx(1.0)


# -- the store -----------------------------------------------------------------


class TestProfileStore:
    def test_archive_ring_and_rows(self):
        store = ProfileStore()
        ref = store.archive(_artifact())
        assert ref["path"] is None  # memory-only store
        assert store.get("query_1")["query_id"] == "query_1"
        assert store.get(ref["key"]) is not None
        rows = store.rows()
        assert len(rows) == 1 and rows[0][0] == "query_1"

    def test_archive_to_disk_through_spi(self, tmp_path):
        store = ProfileStore(archive_dir=str(tmp_path))
        ref = store.archive(_artifact())
        assert store.flush(5.0)
        assert os.path.exists(ref["path"])
        on_disk = json.loads(open(ref["path"]).read())
        assert on_disk["query_id"] == "query_1"

    def test_get_from_disk_survives_restart(self, tmp_path):
        store = ProfileStore(archive_dir=str(tmp_path), synchronous=True)
        store.archive(_artifact())
        fresh = ProfileStore(archive_dir=str(tmp_path))  # new incarnation
        art = fresh.get("query_1")
        assert art is not None and art["query_id"] == "query_1"

    def test_concurrent_archives_produce_distinct_wellformed_files(
        self, tmp_path
    ):
        # the satellite contract: K lanes completing simultaneously ->
        # K distinct artifacts, no torn JSON (SPI write is atomic publish)
        store = ProfileStore(archive_dir=str(tmp_path))
        K = 8

        def complete(i):
            for j in range(5):
                store.archive(
                    _artifact(
                        query_id=f"query_{i}_{j}",
                        sql=f"select {i * 100 + j}",
                        wall=0.01 * (i + 1),
                    )
                )

        threads = [
            threading.Thread(target=complete, args=(i,), daemon=True,
                             name=f"lane-{i}")
            for i in range(K)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10.0)
        assert store.flush(10.0)
        files = [f for f in os.listdir(tmp_path) if f.endswith(".json")]
        assert len(files) == K * 5
        for f in files:  # every artifact parses and carries the invariant
            art = json.loads(open(tmp_path / f).read())
            assert abs(sum(art["phases"].values()) - art["wall_s"]) < 1e-9

    def test_retention_sweep_deletes_only_expired(self, tmp_path):
        t = [1000.0]
        store = ProfileStore(
            archive_dir=str(tmp_path), retention_max_age_s=100.0,
            synchronous=True, clock=lambda: t[0],
        )
        old = store.archive(_artifact(query_id="query_old"))
        os.utime(old["path"], (800.0, 800.0))  # mtime 200s in the past
        young = store.archive(_artifact(query_id="query_young"))
        os.utime(young["path"], (950.0, 950.0))
        deleted = store.sweep()
        assert deleted == [old["path"]]
        assert os.path.exists(young["path"])
        assert not os.path.exists(old["path"])

    def test_retention_count_prunes_oldest_first(self, tmp_path):
        store = ProfileStore(
            archive_dir=str(tmp_path), retention_max_count=2,
            synchronous=True,
        )
        refs = []
        for i in range(4):
            r = store.archive(_artifact(query_id=f"query_{i}"))
            os.utime(r["path"], (100.0 + i, 100.0 + i))
            refs.append(r)
        deleted = store.sweep(now_s=200.0)
        assert sorted(deleted) == sorted([refs[0]["path"], refs[1]["path"]])
        assert os.path.exists(refs[2]["path"])
        assert os.path.exists(refs[3]["path"])

    def test_sweep_ignores_non_artifacts(self, tmp_path):
        (tmp_path / "spool.npz").write_bytes(b"not a profile")
        store = ProfileStore(
            archive_dir=str(tmp_path), retention_max_count=1,
            synchronous=True,
        )
        store.sweep(now_s=1e12)
        assert (tmp_path / "spool.npz").exists()

    def test_ring_bounded(self):
        store = ProfileStore(ring_limit=3)
        for i in range(5):
            store.archive(_artifact(query_id=f"query_{i}"))
        assert len(store.refs()) == 3
        assert store.get("query_0") is None  # rotated out, no disk tier


# -- device-gate telemetry -----------------------------------------------------


def _hist_count(name):
    return REGISTRY.histogram("trino_tpu_" + name).value()


class TestDeviceGate:
    def test_uncontended_step_observes_nothing(self):
        from trino_tpu.runtime.dispatcher import device_slice

        w0 = _hist_count("device_gate_wait_seconds")
        h0 = _hist_count("device_gate_hold_seconds")
        for _ in range(100):
            with device_slice():
                pass
        # zero-cost-when-idle: no wait observed, no hold observed
        assert _hist_count("device_gate_wait_seconds") == w0
        assert _hist_count("device_gate_hold_seconds") == h0

    def test_contended_acquire_observes_wait_and_hold(self):
        from trino_tpu.runtime.dispatcher import device_slice, gate_holder

        w0 = _hist_count("device_gate_wait_seconds")
        h0 = _hist_count("device_gate_hold_seconds")
        holding = threading.Event()
        release = threading.Event()
        seen_holder = []

        def holder():
            with device_slice():
                holding.set()
                release.wait(5.0)

        t = threading.Thread(target=holder, daemon=True, name="gate-holder")
        t.start()
        holding.wait(5.0)
        seen_holder.append(gate_holder())

        def waiter():
            with device_slice():
                pass

        t2 = threading.Thread(target=waiter, daemon=True, name="gate-waiter")
        t2.start()
        time.sleep(0.05)  # let the waiter block
        release.set()
        t.join(5.0)
        t2.join(5.0)
        assert seen_holder == [0]  # occupancy readable while held
        assert gate_holder() == -1  # idle again
        assert _hist_count("device_gate_wait_seconds") == w0 + 1
        # the hold during which the waiter waited was observed
        assert _hist_count("device_gate_hold_seconds") >= h0 + 1

    def test_gate_wait_attributed_to_executing_query(self):
        from trino_tpu.runtime import lifecycle
        from trino_tpu.runtime.dispatcher import device_slice

        ctx = lifecycle.QueryContext("query_gate")
        holding = threading.Event()
        release = threading.Event()

        def holder():
            with device_slice():
                holding.set()
                release.wait(5.0)

        t = threading.Thread(target=holder, daemon=True, name="gate-holder2")
        t.start()
        holding.wait(5.0)
        token = lifecycle.set_current(ctx)
        try:
            done = threading.Event()

            def releaser():
                time.sleep(0.02)
                release.set()
                done.set()

            threading.Thread(
                target=releaser, daemon=True, name="gate-releaser"
            ).start()
            with device_slice():
                pass
        finally:
            lifecycle.reset_current(token)
        t.join(5.0)
        assert ctx.gate_wait_s > 0.0

    def test_reentrant_hold_counts_once(self):
        from trino_tpu.runtime.dispatcher import device_slice, gate_holder

        with device_slice():
            with device_slice():
                assert gate_holder() == 0
            assert gate_holder() == 0  # inner exit must not clear holder
        assert gate_holder() == -1

    def test_uncontended_overhead_measured(self):
        # "measured, not asserted": the timed gate's per-step cost vs the
        # raw RLock it replaced, on this machine, under a VERY generous
        # bound (the budget is one clock read + one non-blocking acquire;
        # 50us/step would be two orders of magnitude over it)
        from trino_tpu.runtime.dispatcher import device_slice

        n = 5000
        raw = threading.RLock()
        t0 = time.perf_counter()
        for _ in range(n):
            with raw:
                pass
        raw_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(n):
            with device_slice():
                pass
        timed_s = time.perf_counter() - t0
        per_step_overhead = max(0.0, timed_s - raw_s) / n
        assert per_step_overhead < 50e-6, (
            f"timed gate overhead {per_step_overhead * 1e6:.2f}us/step "
            f"(timed {timed_s:.4f}s vs raw {raw_s:.4f}s over {n} steps)"
        )

    def test_gate_vocabulary_preregistered(self):
        text = REGISTRY.render_prometheus()
        for name in (
            "trino_tpu_device_gate_wait_seconds",
            "trino_tpu_device_gate_hold_seconds",
            "trino_tpu_device_gate_occupied",
            "trino_tpu_profiles_archived_total",
            "trino_tpu_profiles_pruned_total",
            "trino_tpu_audit_events_total",
        ):
            assert name in text


# -- runner integration --------------------------------------------------------


class TestRunnerIntegration:
    def test_local_execute_archives_artifact(self):
        r = LocalQueryRunner()
        store = attach_profile_store(r, ProfileStore())
        res = r.execute("select count(*) from region")
        assert res.rows == [(5,)]
        art = store.get("query_1")
        assert art is not None
        assert art["state"] == "FINISHED"
        assert art["rows"] == 1
        assert abs(sum(art["phases"].values()) - art["wall_s"]) < 1e-9
        assert art["spans"]  # query_trace defaults on

    def test_failed_statement_archives_with_error_code(self):
        r = LocalQueryRunner()
        store = attach_profile_store(r, ProfileStore())
        with pytest.raises(Exception):
            r.execute("select * from no_such_table")
        arts = [store.get(ref["query_id"]) for ref in store.refs()]
        assert any(a["state"] == "FAILED" for a in arts)

    def test_no_store_means_no_archiving_cost(self):
        r = LocalQueryRunner()
        assert r.profile_store is None  # default: off
        c0 = REGISTRY.counter("trino_tpu_profiles_archived_total").value()
        r.execute("select 1")
        assert (
            REGISTRY.counter("trino_tpu_profiles_archived_total").value()
            == c0
        )

    def test_system_table_and_statistics(self):
        from trino_tpu.runtime.events import CollectingEventListener

        r = LocalQueryRunner()
        attach_profile_store(r, ProfileStore())
        ev = CollectingEventListener()
        r.events.add(ev)
        r.execute("select count(*) from nation")
        rows = r.execute(
            "select query_id, state, wall_s, resource_group, gate_wait_s "
            "from system.runtime.query_profiles"
        ).rows
        assert any(row[0] == "query_1" and row[1] == "FINISHED"
                   for row in rows)
        stats = ev.completed[0].statistics
        assert stats.gate_wait_s == 0.0
        assert stats.profile_key  # the event names its artifact

    def test_mesh_artifact_carries_fragments_and_collectives(self, dist):
        store = attach_profile_store(dist, ProfileStore())
        try:
            dist.execute(
                "select l_returnflag, count(*) from lineitem "
                "group by l_returnflag"
            )
            art = store.get(store.refs()[-1]["query_id"])
            assert art["mesh"].startswith("(8,")
            assert len(art["fragments"]) >= 2
            assert abs(sum(art["phases"].values()) - art["wall_s"]) < 1e-9
            # phases carry the mesh decomposition, not just unattributed
            tracked = sum(
                art["phases"][p]
                for p in ("trace", "compute", "collective", "transfer",
                          "other")
            )
            assert tracked > 0
        finally:
            dist.profile_store = None

    def test_coordinator_profile_endpoint(self):
        import urllib.request

        from trino_tpu.server.coordinator import CoordinatorServer

        r = LocalQueryRunner()
        attach_profile_store(r, ProfileStore())
        server = CoordinatorServer(runner=r, port=0)
        server.start()
        try:
            from trino_tpu.client import Client

            c = Client(f"http://127.0.0.1:{server.port}")
            _, rows = c.execute("select count(*) from region")
            assert [list(r) for r in rows] == [[5]]
            with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/v1/query/q_1/profile",
                timeout=10,
            ) as resp:
                art = json.loads(resp.read().decode())
            assert art["state"] == "FINISHED"
            assert abs(sum(art["phases"].values()) - art["wall_s"]) < 1e-9
        finally:
            server.shutdown()


# -- profile_diff --------------------------------------------------------------


class TestProfileDiff:
    def _pair(self):
        frag_a = [{
            "fragment": 0, "kind": "SOURCE", "wall_s": 0.5,
            "phases_ms": {"compute": 400.0, "transfer": 100.0},
        }]
        frag_b = [{
            "fragment": 0, "kind": "SOURCE", "wall_s": 1.5,
            "phases_ms": {"compute": 400.0, "transfer": 1100.0},
        }]
        a = _artifact(
            query_id="query_a", wall=1.0,
            phases={"compute": 0.4, "transfer": 0.1}, fragments=frag_a,
            coll={"all_gather/broadcast": 1000},
            counters={"exchange_elided": 3},
        )
        b = _artifact(
            query_id="query_b", wall=2.2,
            phases={"compute": 0.4, "transfer": 1.1}, fragments=frag_b,
            gate_wait=0.2, coll={"all_gather/broadcast": 5000},
            counters={"exchange_elided": 1},
        )
        return a, b

    def test_diff_sums_to_wall_delta(self):
        pd = _tool("profile_diff")
        a, b = self._pair()
        rep = pd.diff_artifacts(a, b)
        assert rep["comparable"]
        assert rep["wall_delta_s"] == pytest.approx(1.2)
        assert rep["sums_to_wall"] is True
        assert sum(rep["phases_delta_s"].values()) == pytest.approx(
            rep["wall_delta_s"], abs=1e-9
        )

    def test_dominant_phase_and_fragment_named(self):
        pd = _tool("profile_diff")
        a, b = self._pair()
        rep = pd.diff_artifacts(a, b)
        assert rep["dominant_phase"] == "transfer"
        assert rep["dominant_fragment"] == 0
        assert rep["dominant"]["phase"] == "transfer"
        assert rep["collective_bytes_delta"] == {
            "all_gather/broadcast": 4000
        }
        assert rep["counters_delta"] == {"exchange_elided": -2}
        assert rep["gate_wait_delta_s"] == pytest.approx(0.2)

    def test_null_diff_contract(self):
        pd = _tool("profile_diff")
        a, _ = self._pair()
        rep = pd.diff_artifacts(a, a)
        assert rep["wall_delta_s"] == 0.0
        assert all(v == 0.0 for v in rep["phases_delta_s"].values())
        assert pd.null_diff_ok(rep)

    def test_null_diff_rejects_real_drift(self):
        pd = _tool("profile_diff")
        a, b = self._pair()
        assert not pd.null_diff_ok(pd.diff_artifacts(a, b))

    def test_incompatible_versions_refused(self):
        pd = _tool("profile_diff")
        a, b = self._pair()
        b = dict(b, version=99)
        with pytest.raises(ValueError):
            pd.diff_artifacts(a, b)

    def test_different_statements_flagged_not_comparable(self):
        pd = _tool("profile_diff")
        a, _ = self._pair()
        b = _artifact(query_id="query_c", sql="select 2", wall=1.0)
        assert pd.diff_artifacts(a, b)["comparable"] is False

    def test_cli_threshold_exit_codes(self, tmp_path):
        pd = _tool("profile_diff")
        a, b = self._pair()
        pa, pb = tmp_path / "a.json", tmp_path / "b.json"
        pa.write_text(json.dumps(a))
        pb.write_text(json.dumps(b))
        # 120% drift over a 10% threshold -> exit 2
        assert pd.main([str(pa), str(pb)]) == 2
        # same artifact -> inside threshold -> exit 0
        assert pd.main([str(pa), str(pa)]) == 0
        # generous threshold swallows the drift
        assert pd.main([str(pa), str(pb), "--threshold", "5.0"]) == 0

    def test_mesh_section_mode(self):
        pd = _tool("profile_diff")
        old = {
            "q3_mesh8_warm_s": 5.985, "q3_local_warm_s": 3.6998,
            "q3_counters": {"exchange_elided": 3},
        }
        new = {
            "q3_mesh8_warm_s": 9.376, "q3_local_warm_s": 2.104,
            "q3_counters": {"exchange_elided": 3},
        }
        rep = pd.diff_mesh_sections(old, new, "q3")
        assert rep["mesh_wall_delta_s"] == pytest.approx(3.391)
        assert rep["ratio"]["old"] == pytest.approx(1.618, abs=1e-3)
        assert rep["ratio"]["new"] == pytest.approx(4.456, abs=1e-2)
        assert rep.get("counters_delta") == {}


# -- compare_bench check_drift -------------------------------------------------


def _drift_section(**over):
    sec = {
        "schema": "sf1",
        "query": "q3",
        "baseline": {"ref": "PR3", "mesh_warm_s": 5.985,
                     "local_warm_s": 3.6998, "ratio": 1.618},
        "current": {"mesh_warm_s": 3.6, "local_warm_s": 1.45,
                    "ratio": 2.5, "matches_local": True,
                    "profile_ref": {"key": "k"}},
        "mesh_wall_delta_s": -2.4,
        "local_wall_delta_s": -2.25,
        "ratio_factors": {"mesh": 0.6, "local_inverse": 2.55},
        "attribution": {
            "dominant_phase": "transfer", "dominant_fragment": 1,
            "sums_to_wall": True, "phases_s": {},
        },
        "null_diff": {"query": "q6", "pass": True, "sums_to_wall": True,
                      "wall_delta_s": 0.001, "max_phase_delta_s": 0.002},
    }
    sec.update(over)
    return sec


class TestCheckDrift:
    def test_valid_section_passes(self):
        cb = _tool("compare_bench")
        assert cb.check_drift(_drift_section()) == []

    def test_missing_keys_flagged(self):
        cb = _tool("compare_bench")
        sec = _drift_section()
        del sec["ratio_factors"]
        assert cb.check_drift(sec)

    def test_unnamed_dominant_fails(self):
        cb = _tool("compare_bench")
        sec = _drift_section()
        sec["attribution"]["dominant_phase"] = None
        assert any("dominant_phase" in v for v in cb.check_drift(sec))
        sec = _drift_section()
        sec["attribution"]["dominant_fragment"] = None
        assert any("dominant_fragment" in v for v in cb.check_drift(sec))

    def test_broken_conservation_fails(self):
        cb = _tool("compare_bench")
        sec = _drift_section()
        sec["attribution"]["sums_to_wall"] = False
        assert any("sums_to_wall" in v for v in cb.check_drift(sec))

    def test_failed_null_diff_fails(self):
        cb = _tool("compare_bench")
        sec = _drift_section()
        sec["null_diff"]["pass"] = False
        assert any("null_diff" in v for v in cb.check_drift(sec))

    def test_missing_drift_section_is_skipped_not_failed(self):
        cb = _tool("compare_bench")
        violations, skipped = cb.check_extra({})
        assert not any("drift" in v for v in violations)
        assert any("drift" in s for s in skipped)

    def test_checked_in_drift_section_passes(self):
        cb = _tool("compare_bench")
        with open(os.path.join(REPO_ROOT, "BENCH_EXTRA.json")) as fh:
            extra = json.load(fh)
        drift = extra.get("drift")
        assert isinstance(drift, dict), (
            "BENCH_EXTRA.json must carry the recorded Q3 drift "
            "attribution (run tools/drift_bench.py)"
        )
        assert cb.check_drift(drift) == []
        # the first real catch is recorded with the fragment named
        assert drift["attribution"]["dominant_phase"]
        assert drift["attribution"]["dominant_fragment"] is not None


# -- audit log -----------------------------------------------------------------


class TestAuditLog:
    def test_one_line_per_completion_with_fields(self, tmp_path):
        from trino_tpu.telemetry.audit import QueryAuditLog

        path = str(tmp_path / "audit.jsonl")
        r = LocalQueryRunner()
        r.events.add(QueryAuditLog(path))
        r.execute("select count(*) from region")
        with pytest.raises(Exception):
            r.execute("select * from missing_table")
        lines = [
            json.loads(l)
            for l in open(path).read().splitlines() if l
        ]
        assert len(lines) == 2
        ok, bad = lines
        assert ok["state"] == "FINISHED" and ok["rows"] == 1
        assert ok["wall_s"] > 0
        assert "gate_wait_s" in ok and "peak_memory_bytes" in ok
        assert bad["state"] == "FAILED"
        assert bad["error_type"] == "USER_ERROR"

    def test_size_based_rotation(self, tmp_path):
        from trino_tpu.runtime.events import QueryCompletedEvent
        from trino_tpu.telemetry.audit import QueryAuditLog

        path = str(tmp_path / "audit.jsonl")
        log = QueryAuditLog(path, rotate_bytes=600, rotate_keep=2)
        for i in range(12):
            log.query_completed(
                QueryCompletedEvent(
                    f"query_{i}", "select 1", "FINISHED", 0.0, 0.1
                )
            )
        assert os.path.exists(path + ".1")  # rotation happened
        # live segment stays under the knob
        assert os.path.getsize(path) <= 600
        # every surviving line still parses (rotation never tears lines)
        for p in (path, path + ".1"):
            for line in open(p).read().splitlines():
                if line:
                    json.loads(line)
        # rotate_keep bounds the segment chain
        assert not os.path.exists(path + ".3")

    def test_unwritable_path_fails_at_startup(self, tmp_path):
        from trino_tpu.telemetry.audit import QueryAuditLog

        blocker = tmp_path / "blocker"
        blocker.write_text("a file where a directory must go")
        with pytest.raises(OSError):
            QueryAuditLog(str(blocker / "x.jsonl"))

    def test_config_attach_is_noop_without_knob(self):
        from trino_tpu.telemetry.audit import attach_audit_log

        r = LocalQueryRunner()
        assert attach_audit_log(r) is None

    def test_audit_counts_metric(self, tmp_path):
        from trino_tpu.telemetry.audit import QueryAuditLog

        c0 = REGISTRY.counter("trino_tpu_audit_events_total").value()
        r = LocalQueryRunner()
        r.events.add(QueryAuditLog(str(tmp_path / "a.jsonl")))
        r.execute("select 1")
        assert (
            REGISTRY.counter("trino_tpu_audit_events_total").value()
            == c0 + 1
        )


# -- lane safety ---------------------------------------------------------------


class TestLaneSafety:
    def test_per_statement_handles_resolve_through_contextvar(self):
        from trino_tpu.runtime import lifecycle

        r = LocalQueryRunner()
        prof_a, prof_b = object(), object()
        ctx_a = lifecycle.QueryContext("query_a")
        ctx_b = lifecycle.QueryContext("query_b")
        ctx_a.mesh_profile = prof_a
        ctx_b.mesh_profile = prof_b
        results = {}

        def read(name, ctx):
            token = lifecycle.set_current(ctx)
            try:
                results[name] = r.last_mesh_profile
            finally:
                lifecycle.reset_current(token)

        ta = threading.Thread(target=read, args=("a", ctx_a), daemon=True,
                              name="lane-a")
        tb = threading.Thread(target=read, args=("b", ctx_b), daemon=True,
                              name="lane-b")
        ta.start(); tb.start(); ta.join(5.0); tb.join(5.0)
        assert results["a"] is prof_a
        assert results["b"] is prof_b
        assert r.last_mesh_profile is None  # no fallback written

    def test_concurrent_traced_statements_keep_their_own_traces(self):
        # K lanes racing EXPLAIN ANALYZE VERBOSE on ONE shared runner:
        # each rendered trace must carry ITS OWN statement's sql (the
        # pre-fix shared runner._tracer attribute raced and could render a
        # neighbor's tree)
        r = LocalQueryRunner()
        K, iters = 4, 3
        failures = []

        def client(i):
            sql = f"explain analyze verbose select {i} + 0"
            for _ in range(iters):
                try:
                    text = "\n".join(
                        row[0] for row in r.execute(sql).rows
                    )
                    tj = text.split("Trace JSON: ", 1)[1]
                    trace = json.loads(tj)
                    sqls = [
                        e["args"]["sql"]
                        for e in trace["traceEvents"]
                        if e["name"] == "query"
                    ]
                    if sqls != [sql]:  # a neighbor's sql = crossed tracer
                        failures.append((i, sqls))
                except Exception as e:
                    failures.append((i, repr(e)))

        threads = [
            threading.Thread(target=client, args=(i,), daemon=True,
                             name=f"explain-lane-{i}")
            for i in range(K)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60.0)
        assert not failures, failures[:3]

    def test_concurrent_lanes_archive_distinct_artifacts(self, tmp_path):
        # K lanes completing simultaneously through ONE shared runner +
        # store: K distinct artifacts, each attributed to its own sql
        r = LocalQueryRunner()
        store = attach_profile_store(
            r, ProfileStore(archive_dir=str(tmp_path))
        )
        K = 4
        errors = []

        def client(i):
            try:
                r.execute(f"select {i} * 10")
            except Exception as e:
                errors.append(repr(e))

        threads = [
            threading.Thread(target=client, args=(i,), daemon=True,
                             name=f"archive-lane-{i}")
            for i in range(K)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
        assert not errors
        assert store.flush(10.0)
        arts = [store.get(ref["query_id"]) for ref in store.refs()]
        sqls = sorted(a["sql"] for a in arts)
        assert sqls == sorted(f"select {i} * 10" for i in range(K))
        # each artifact's rows/wall belong to its own statement
        for a in arts:
            assert a["state"] == "FINISHED"
            assert abs(sum(a["phases"].values()) - a["wall_s"]) < 1e-9

    def test_queries_system_table_sees_gate_columns(self):
        # QueryStatistics carries the new gate/admission fields end to end
        from trino_tpu.runtime.events import CollectingEventListener
        from trino_tpu.runtime.resource_groups import (
            ResourceGroupConfig,
            ResourceGroupManager,
        )
        from trino_tpu.runtime.dispatcher import QueryDispatcher

        r = LocalQueryRunner()
        ev = CollectingEventListener()
        r.events.add(ev)
        mgr = ResourceGroupManager(
            ResourceGroupConfig("global", hard_concurrency=2, max_queued=8)
        )
        d = QueryDispatcher(r, mgr, lanes=2)
        ticket = d.enqueue()
        ticket.wait()
        d.run_admitted(ticket, lambda lane: lane.execute("select 7"))
        stats = ev.completed[-1].statistics
        assert stats.group == "global"
        assert stats.queued_s >= 0.0
        assert stats.gate_wait_s >= 0.0
