"""DDL / DML / utility statement tests (reference style: TestMemoryConnector
+ AbstractTestEngineOnlyQueries' SHOW/EXPLAIN coverage)."""

import pytest

pytestmark = pytest.mark.smoke

from trino_tpu.runtime.runner import LocalQueryRunner


@pytest.fixture()
def runner():
    return LocalQueryRunner(catalog="memory", schema="default")


def test_create_insert_select(runner):
    runner.execute("create table t (a bigint, b varchar)")
    r = runner.execute("insert into t values (1, 'x'), (2, 'y'), (null, 'z')")
    assert r.rows == [(3,)]
    out = runner.execute("select a, b from t order by b")
    assert out.rows == [(1, "x"), (2, "y"), (None, "z")]
    runner.execute("insert into t values (4, 'w')")
    out = runner.execute("select count(*), sum(a) from t")
    assert out.rows == [(4, 7)]


def test_ctas_from_tpch(runner):
    runner.execute("create table nations as select n_name, n_regionkey from tpch.tiny.nation")
    out = runner.execute("select count(*) from nations")
    assert out.rows == [(25,)]
    out = runner.execute(
        "select n_regionkey, count(*) from nations group by n_regionkey order by 1"
    )
    assert out.rows == [(i, 5) for i in range(5)]


def test_insert_column_list(runner):
    runner.execute("create table t2 (a bigint, b varchar, c double)")
    runner.execute("insert into t2 (b, a) select 'v', 9")
    out = runner.execute("select a, b, c from t2")
    assert out.rows == [(9, "v", None)]


def test_drop_table(runner):
    runner.execute("create table gone (x bigint)")
    assert "gone" in [r[0] for r in runner.execute("show tables").rows]
    runner.execute("drop table gone")
    assert "gone" not in [r[0] for r in runner.execute("show tables").rows]
    runner.execute("drop table if exists gone")


def test_show_statements(runner):
    cats = [r[0] for r in runner.execute("show catalogs").rows]
    assert "tpch" in cats and "memory" in cats
    tables = [r[0] for r in runner.execute("show tables from tpch.tiny").rows]
    assert "lineitem" in tables and "orders" in tables
    cols = runner.execute("describe tpch.tiny.region").rows
    assert ("r_regionkey", "bigint") in cols


def test_use_and_set_session(runner):
    runner.execute("use tpch.tiny")
    assert runner.execute("select count(*) from region").rows == [(5,)]
    runner.execute("set session target_splits = 2")
    assert runner.properties.get("target_splits") == 2
    with pytest.raises(KeyError):
        runner.execute("set session no_such_knob = 1")


def test_explain(runner):
    out = runner.execute("explain select count(*) from tpch.tiny.region")
    text = "\n".join(r[0] for r in out.rows)
    assert "Aggregation" in text and "TableScan" in text


def test_explain_analyze(runner):
    out = runner.execute("explain analyze select count(*) from tpch.tiny.nation")
    text = "\n".join(r[0] for r in out.rows)
    assert "rows=" in text and "TableScan" in text


@pytest.mark.smoke
def test_show_create_table(runner):
    runner.execute("create table memory.default.sct (a bigint, s varchar)")
    ddl = runner.execute("show create table memory.default.sct").rows[0][0]
    assert "CREATE TABLE memory.default.sct" in ddl
    assert "a bigint" in ddl and "s varchar" in ddl


@pytest.mark.smoke
def test_alter_table(runner):
    runner.execute("create table memory.default.alt (a bigint, b varchar)")
    runner.execute("insert into memory.default.alt values (1, 'x'), (2, 'y')")
    runner.execute("alter table memory.default.alt add column c double")
    assert sorted(runner.execute("select * from memory.default.alt").rows) == [
        (1, "x", None), (2, "y", None),
    ]
    runner.execute("alter table memory.default.alt rename column b to bb")
    cols = runner.execute("show columns from memory.default.alt").rows
    assert [c[0] for c in cols] == ["a", "bb", "c"]
    runner.execute("alter table memory.default.alt drop column a")
    assert sorted(runner.execute("select * from memory.default.alt").rows) == [
        ("x", None), ("y", None),
    ]
    runner.execute("alter table memory.default.alt rename to memory.default.alt2")
    tables = runner.execute("show tables from memory.default").rows
    assert ("alt2",) in tables and ("alt",) not in tables
