"""Tests for the collective-uniformity pass (verify/collectives.py):
static enumeration over hand-built fragments, rejection of a per-worker-
conditional collective (the SPMD divergence deadlock), the signature
matcher device_residency uses, and the strict-mode wiring.  The full
TPC-H + TPC-DS fragment sweep is `slow` (CI runs it standalone via
`python -m trino_tpu.verify.collectives`); tier-1 covers the machinery on
hand-built fragments plus one real plan."""

from __future__ import annotations

import pytest

from trino_tpu import types as T
from trino_tpu import verify as V
from trino_tpu.planner import plan as P
from trino_tpu.planner.fragmenter import (
    FIXED_HASH,
    SINGLE,
    SOURCE,
    PartitioningHandle,
    PlanFragment,
    RemoteSourceNode,
    SubPlan,
)
from trino_tpu.verify.collectives import (
    check_collective_uniformity,
    collective_signature,
    fragment_collectives,
    signature_problems,
)


def _sym(name, typ=T.BIGINT):
    return P.Symbol(name, typ)


def _scan(*symbols):
    from trino_tpu.connectors.api import ColumnMeta, TableHandle, TableMetadata

    handle = TableHandle("tpch", "tiny", "lineitem")
    meta = TableMetadata(
        "tiny", "lineitem",
        tuple(ColumnMeta(s.name, s.type) for s in symbols),
    )
    return P.TableScanNode(handle, meta, [(s, s.name) for s in symbols])


def _sub(root, kind=FIXED_HASH, fid=0, children=()):
    return SubPlan(
        PlanFragment(fid, root, PartitioningHandle(kind)), list(children)
    )


def _child(root, fid, kind=SOURCE):
    return _sub(root, kind=kind, fid=fid)


class TestEnumeration:
    def test_repartition_agg_fragment(self):
        a = _sym("a")
        child = _child(_scan(a), fid=1)
        cnt = _sym("c")
        agg = P.AggregationNode(
            RemoteSourceNode(1, [a], "repartition", [a]),
            [a],
            [(cnt, P.Aggregation("count", [a.ref()]))],
        )
        cols, violations = fragment_collectives(_sub(agg, children=[child]))
        assert violations == []
        assert [(c.kind, c.purpose) for c in cols] == [
            ("gather", "capacity_sizing"),
            ("all_to_all", "repartition"),
        ]
        assert all(c.guard == "static" for c in cols)

    def test_broadcast_join_fragment(self):
        k = _sym("k")
        j = _sym("j")
        join = P.JoinNode(
            "inner",
            _scan(k),
            RemoteSourceNode(1, [j], "broadcast"),
            [(k, j)],
            None,
            "broadcast",
        )
        cols, violations = fragment_collectives(_sub(join))
        assert violations == []
        assert [(c.kind, c.purpose) for c in cols] == [
            ("reduce", "dynamic_filter"),
            ("all_gather", "broadcast"),
            ("gather", "capacity_sizing"),
        ]
        # the speculative expansion's overflow read is REDUCED, not static:
        # its retry loop is legal because every worker sees the same flag
        assert cols[-1].guard == "reduced"

    def test_partitioned_join_places_build_before_probe(self):
        k, j = _sym("k"), _sym("j")
        join = P.JoinNode(
            "inner",
            RemoteSourceNode(1, [k], "repartition", [k]),
            RemoteSourceNode(2, [j], "repartition", [j]),
            [(k, j)],
            None,
            "partitioned",
        )
        cols, _ = fragment_collectives(_sub(join))
        kinds = [(c.kind, c.purpose) for c in cols]
        assert kinds == [
            ("reduce", "dynamic_filter"),
            ("all_to_all", "repartition"),  # build side first
            ("all_to_all", "repartition"),
            ("gather", "capacity_sizing"),
        ]

    def test_varchar_keys_make_dynamic_filter_elidable(self):
        k, j = _sym("k", T.VARCHAR), _sym("j", T.VARCHAR)
        join = P.JoinNode(
            "inner", _scan(k), RemoteSourceNode(1, [j], "broadcast"),
            [(k, j)], None, "broadcast",
        )
        cols, _ = fragment_collectives(_sub(join))
        assert ("reduce", "dynamic_filter") not in [
            (c.kind, c.purpose) for c in cols
        ]

    def test_single_fragment_has_no_mesh_collectives(self):
        a = _sym("a")
        root = P.LimitNode(RemoteSourceNode(1, [a], "gather"), 10)
        cols, violations = fragment_collectives(_sub(root, kind=SINGLE))
        assert cols == () and violations == []

    def test_gather_feeding_distributed_fragment_is_rejected(self):
        a = _sym("a")
        root = P.FilterNode(
            RemoteSourceNode(1, [a], "gather"),
            P.Symbol("p", T.BOOLEAN).ref(),
        )
        _, violations = fragment_collectives(_sub(root))
        assert [v.rule for v in violations] == ["collective-unsupported"]


class TestUniformity:
    def _divergent_join(self):
        """The hand-built divergent fragment: a speculative join whose
        retry collective is DECLARED conditional on per-worker data — the
        exact bug the pass exists to reject."""
        k, j = _sym("k"), _sym("j")
        join = P.JoinNode(
            "inner",
            _scan(k),
            RemoteSourceNode(1, [j], "broadcast"),
            [(k, j)],
            None,
            "broadcast",
        )
        # a per-worker branch around the overflow/retry path: worker-local
        # overflow flags instead of the reduced one
        join.collective_condition = "per_worker:local_overflow_flag"
        child = _child(_scan(j), fid=1)
        return _sub(join, children=[child])

    def test_per_worker_conditional_collective_is_rejected(self):
        violations = check_collective_uniformity(self._divergent_join())
        # the declared per-worker condition gates every collective the node
        # issues (filter reduce, broadcast, overflow gather): all rejected
        assert violations
        assert {v.rule for v in violations} == {"collective-divergence"}
        assert "per_worker:local_overflow_flag" in str(violations[0])
        assert "deadlock" in str(violations[0])

    def test_strict_enforcement_raises(self):
        with pytest.raises(V.PlanViolation) as ei:
            V.enforce(
                check_collective_uniformity(self._divergent_join()), "strict"
            )
        assert ei.value.rule == "collective-divergence"

    def test_reduced_condition_is_accepted(self):
        sub = self._divergent_join()
        sub.fragment.root.collective_condition = "reduced"
        assert check_collective_uniformity(sub) == []

    def test_unconditional_is_accepted(self):
        sub = self._divergent_join()
        del sub.fragment.root.collective_condition
        assert check_collective_uniformity(sub) == []


class TestSignature:
    def test_signature_covers_mesh_kinds_only(self):
        k, j = _sym("k"), _sym("j")
        join = P.JoinNode(
            "inner", _scan(k), RemoteSourceNode(1, [j], "broadcast"),
            [(k, j)], None, "broadcast",
        )
        sub = _sub(join, children=[_child(_scan(j), fid=1)])
        sig = collective_signature(sub)
        assert sig[0] == (
            ("reduce", "dynamic_filter", False),
            ("all_gather", "broadcast", False),
        )
        assert sig[1] == ()

    def test_matcher_accepts_exact_and_elided(self):
        expected = {
            0: (
                ("all_to_all", "repartition", True),  # elidable
                ("all_gather", "broadcast", False),
            )
        }
        ok_full = {0: (("all_to_all", "repartition"), ("all_gather", "broadcast"))}
        ok_elided = {0: (("all_gather", "broadcast"),)}
        assert signature_problems(expected, ok_full) == []
        assert signature_problems(expected, ok_elided) == []

    def test_matcher_backtracks_over_same_kind_elidable(self):
        """An elided entry must not greedily consume the issued collective
        that belongs to a LATER required entry of the same (kind, purpose):
        one issued repartition satisfies either slot, so the sequence with
        the elidable one skipped must match."""
        expected = {
            0: (
                ("all_to_all", "repartition", True),   # elided at runtime
                ("all_to_all", "repartition", False),  # the join's own
            )
        }
        one_issued = {0: (("all_to_all", "repartition"),)}
        both_issued = {
            0: (("all_to_all", "repartition"), ("all_to_all", "repartition"))
        }
        assert signature_problems(expected, one_issued) == []
        assert signature_problems(expected, both_issued) == []
        assert signature_problems(expected, {0: ()}), "required slot unmet"

    def test_matcher_rejects_missing_extra_and_reordered(self):
        expected = {
            0: (
                ("all_to_all", "repartition", False),
                ("all_gather", "broadcast", False),
            )
        }
        missing = {0: (("all_to_all", "repartition"),)}
        extra = {
            0: (
                ("all_to_all", "repartition"),
                ("all_gather", "broadcast"),
                ("all_gather", "broadcast"),
            )
        }
        reordered = {
            0: (
                ("all_gather", "broadcast"),
                ("all_to_all", "repartition"),
            )
        }
        for bad in (missing, extra, reordered):
            assert signature_problems(expected, bad), bad
        assert signature_problems(expected, {}) != []

    def test_real_plan_signature_records_per_fragment(self):
        """One real distributed plan end to end: the runner records the
        static signature at create_subplan time and the shape matches the
        agg-over-repartition fragment layout."""
        from trino_tpu.parallel.runner import DistributedQueryRunner

        r = DistributedQueryRunner(n_workers=8)
        r.properties.set("verify_plan", "strict")
        r.create_subplan(
            r.create_plan(
                "select l_returnflag, count(*) from lineitem "
                "group by l_returnflag"
            )
        )
        sig = r.last_collective_signature
        flat = [e for seq in sig.values() for e in seq]
        assert ("all_to_all", "repartition", False) in flat


@pytest.mark.slow
class TestFullSweep:
    def test_every_tpch_tpcds_fragment_is_uniform(self):
        """The acceptance sweep: every distributed TPC-H + TPC-DS fragment
        verifies divergence-free in strict mode (CI also runs this gate
        standalone, outside pytest)."""
        from trino_tpu.verify.collectives import verify_benchmarks

        assert verify_benchmarks(8) > 1000
