"""MAP type + scalar function tests (reference: TestMapOperators.java,
operator/scalar/MapConstructor/MapKeys/MapValues/MapConcatFunction)."""

import pytest

from trino_tpu import types as T

pytestmark = pytest.mark.smoke


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime.runner import LocalQueryRunner

    return LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=2)


def test_map_type_parse():
    mt = T.parse_type("map(varchar, bigint)")
    assert isinstance(mt, T.MapType)
    assert T.is_string_kind(mt.key) and mt.value == T.BIGINT
    nested = T.parse_type("map(bigint, array(double))")
    assert isinstance(nested.value, T.ArrayType)


def test_map_subscript(runner):
    rows = runner.execute(
        "select map(array['a','b'], array[1,2])['b']"
    ).rows
    assert rows == [(2,)]


def test_map_element_at_missing_is_null(runner):
    rows = runner.execute(
        "select element_at(map(array['x'], array[10]), 'y')"
    ).rows
    assert rows == [(None,)]


def test_map_keys_values_cardinality(runner):
    rows = runner.execute(
        "select cardinality(m), map_keys(m), map_values(m) "
        "from (select map(array[1,2,3], array[40,50,60]) m)"
    ).rows
    assert rows == [(3, [1, 2, 3], [40, 50, 60])]


def test_map_concat_later_wins(runner):
    rows = runner.execute(
        "select map_concat(map(array[1,2], array[10,20]), "
        "map(array[2,3], array[99,30]))"
    ).rows
    assert rows == [({1: 10, 2: 99, 3: 30},)]


def test_map_string_values(runner):
    rows = runner.execute(
        "select map(array[1,2], array['x','y'])[2]"
    ).rows
    assert rows == [("y",)]


def test_map_mismatched_lengths_null(runner):
    rows = runner.execute(
        "select map(array[1,2], array[5])"
    ).rows
    assert rows == [(None,)]


def test_map_over_table_rows(runner):
    """Maps built per-row from table columns survive exchange/render."""
    rows = runner.execute(
        "select map(array[n_nationkey], array[n_regionkey])[n_nationkey] r, "
        "n_regionkey from nation order by n_nationkey limit 3"
    ).rows
    for got, expect in rows:
        assert got == expect
