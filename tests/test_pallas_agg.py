"""Pallas MXU aggregation kernel (ops/pallas_agg.py): correctness vs the
XLA formulation and end-to-end behind the `pallas_agg` session property.
On CPU the kernel runs in interpreter mode; the TPU path compiles the same
program."""

import jax.numpy as jnp
import numpy as np
import pytest

from tests.test_e2e import assert_rows_match
from trino_tpu.ops.pallas_agg import grouped_sums_pallas, grouped_sums_xla
from trino_tpu.runtime.runner import LocalQueryRunner


def test_kernel_matches_xla():
    rng = np.random.default_rng(7)
    n, k, g = 4096, 5, 9
    gids = jnp.asarray(rng.integers(0, g, n), jnp.int32)
    mask = jnp.asarray(rng.random(n) < 0.7)
    vals = jnp.asarray(rng.random((n, k)), jnp.float32)
    a = grouped_sums_pallas(gids, mask, vals, n_groups=g, interpret=True)
    b = grouped_sums_xla(gids, mask, vals, g)
    assert jnp.allclose(a, b, atol=1e-2)


def test_kernel_multi_block():
    rng = np.random.default_rng(8)
    n, g = 8192, 3  # 4 grid steps at block 2048
    gids = jnp.asarray(rng.integers(0, g, n), jnp.int32)
    mask = jnp.ones(n, bool)
    vals = jnp.ones((n, 1), jnp.float32)
    a = grouped_sums_pallas(gids, mask, vals, n_groups=g, interpret=True)
    counts = np.bincount(np.asarray(gids), minlength=g)
    assert np.allclose(np.asarray(a)[:, 0], counts)


def test_query_with_pallas_agg_matches_default():
    sql = (
        "select o_orderstatus, o_orderpriority, count(*), "
        "sum(cast(o_totalprice as double)), avg(cast(o_totalprice as double)) "
        "from orders group by o_orderstatus, o_orderpriority"
    )
    base = LocalQueryRunner(catalog="tpch", schema="tiny")
    expected = base.execute(sql).rows

    fast = LocalQueryRunner(catalog="tpch", schema="tiny")
    fast.execute("set session pallas_agg = true")
    actual = fast.execute(sql).rows
    assert_rows_match(actual, expected, ordered=False, atol=0.5)


def test_matmul_direct_sums_exact():
    """The one-hot GEMM aggregation path (TPU default) is exact for int,
    short-decimal, long-decimal, and double sums — forced on here since
    tests run on CPU where the segmented path is the default."""
    from decimal import Decimal

    import trino_tpu.ops.aggregation as agg
    from trino_tpu.runtime.runner import LocalQueryRunner

    q = (
        "select l_returnflag, sum(l_quantity), sum(l_extendedprice), "
        "sum(l_extendedprice * (1 - l_discount)), avg(l_quantity), "
        "count(*), count(l_comment) from lineitem group by l_returnflag "
        "order by l_returnflag"
    )
    # oracle FIRST, through whatever (segmented) steps are already cached
    expected = LocalQueryRunner(
        catalog="tpch", schema="tiny", target_splits=4
    ).execute(q).rows

    orig = agg.AggregationOperator._matmul_direct_sums
    orig_cache = agg._STEP_CACHE
    called = {"n": 0}

    def forced(self, batch, live, gid, prod):
        self.force_matmul = True
        out = orig(self, batch, live, gid, prod)
        if out is not None:
            called["n"] += 1
        return out

    # fresh step cache: the jitted steps bake the (forced) matmul path into
    # their traces, so they must neither reuse earlier unforced traces nor
    # leak forced ones back into the shared process-level cache
    agg.AggregationOperator._matmul_direct_sums = forced
    agg._STEP_CACHE = {}
    try:
        r = LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=4)
        rows = r.execute(q).rows
        assert called["n"] > 0, "matmul path did not engage"
        assert rows == expected
    finally:
        agg.AggregationOperator._matmul_direct_sums = orig
        agg._STEP_CACHE = orig_cache
