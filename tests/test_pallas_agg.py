"""Pallas MXU aggregation kernel (ops/pallas_agg.py): correctness vs the
XLA formulation and end-to-end behind the `pallas_agg` session property.
On CPU the kernel runs in interpreter mode; the TPU path compiles the same
program."""

import jax.numpy as jnp
import numpy as np
import pytest

from tests.test_e2e import assert_rows_match
from trino_tpu.ops.pallas_agg import grouped_sums_pallas, grouped_sums_xla
from trino_tpu.runtime.runner import LocalQueryRunner


def test_kernel_matches_xla():
    rng = np.random.default_rng(7)
    n, k, g = 4096, 5, 9
    gids = jnp.asarray(rng.integers(0, g, n), jnp.int32)
    mask = jnp.asarray(rng.random(n) < 0.7)
    vals = jnp.asarray(rng.random((n, k)), jnp.float32)
    a = grouped_sums_pallas(gids, mask, vals, n_groups=g, interpret=True)
    b = grouped_sums_xla(gids, mask, vals, g)
    assert jnp.allclose(a, b, atol=1e-2)


def test_kernel_multi_block():
    rng = np.random.default_rng(8)
    n, g = 8192, 3  # 4 grid steps at block 2048
    gids = jnp.asarray(rng.integers(0, g, n), jnp.int32)
    mask = jnp.ones(n, bool)
    vals = jnp.ones((n, 1), jnp.float32)
    a = grouped_sums_pallas(gids, mask, vals, n_groups=g, interpret=True)
    counts = np.bincount(np.asarray(gids), minlength=g)
    assert np.allclose(np.asarray(a)[:, 0], counts)


def test_query_with_pallas_agg_matches_default():
    sql = (
        "select o_orderstatus, o_orderpriority, count(*), "
        "sum(cast(o_totalprice as double)), avg(cast(o_totalprice as double)) "
        "from orders group by o_orderstatus, o_orderpriority"
    )
    base = LocalQueryRunner(catalog="tpch", schema="tiny")
    expected = base.execute(sql).rows

    fast = LocalQueryRunner(catalog="tpch", schema="tiny")
    fast.execute("set session pallas_agg = true")
    actual = fast.execute(sql).rows
    assert_rows_match(actual, expected, ordered=False, atol=0.5)
