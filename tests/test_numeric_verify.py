"""Numeric-safety verifier tests (trino_tpu/verify/numeric.py + ranges.py):
the interval lattice, the per-rule negative tests the acceptance demands
(a hand-built overflow / scale-mismatch / dropped-validity expression each
raises the right rule), the plan-level licensing pass, and the TPC-H +
TPC-DS sweep gate (full sweep marked slow; CI also runs it directly via
`python -m trino_tpu.verify.numeric`)."""

from decimal import Decimal

import pytest

from trino_tpu import types as T
from trino_tpu.expr.ir import Call, Form, InputRef, Literal, SpecialForm
from trino_tpu.verify import ranges as R
from trino_tpu.verify.numeric import (
    Analyzer,
    Env,
    Fact,
    analyze_expr,
    license_decimal_sums,
    row_upper_bound,
    sum_certificate,
)

pytestmark = pytest.mark.smoke


# -- the interval lattice ------------------------------------------------------


class TestInterval:
    def test_arith(self):
        a = R.Interval(-3, 10)
        b = R.Interval(2, 5)
        assert a.add(b) == R.Interval(-1, 15)
        assert a.sub(b) == R.Interval(-8, 8)
        assert a.mul(b) == R.Interval(-15, 50)
        assert a.neg() == R.Interval(-10, 3)

    def test_unbounded_propagates(self):
        top = R.Interval.top()
        assert R.Interval(1, 2).add(top) == top
        assert R.Interval(1, 2).mul(top) == top
        assert top.max_abs() is None

    def test_union_and_within(self):
        a = R.Interval(0, 5)
        b = R.Interval(-2, 3)
        assert a.union(b) == R.Interval(-2, 5)
        assert b.within(R.Interval(-10, 10))
        assert not R.Interval(-11, 0).within(R.Interval(-10, 10))
        assert a.within(R.Interval.top())

    def test_scale_pow10(self):
        assert R.Interval(-3, 7).scale_pow10(2) == R.Interval(-300, 700)
        # downscale is conservative (never tightens below the truth)
        d = R.Interval(-150, 250).scale_pow10(-2)
        assert d.lo <= -2 and d.hi >= 3

    def test_exactness_soundness_exhaustive(self):
        """Interval ops over small ranges contain every concrete result."""
        import itertools

        vals = [-7, -1, 0, 2, 9]
        for lo1, hi1, lo2, hi2 in itertools.product(vals, repeat=4):
            if lo1 > hi1 or lo2 > hi2:
                continue
            a, b = R.Interval(lo1, hi1), R.Interval(lo2, hi2)
            for x in range(lo1, hi1 + 1):
                for y in range(lo2, hi2 + 1):
                    assert a.add(b).lo <= x + y <= a.add(b).hi
                    assert a.mul(b).lo <= x * y <= a.mul(b).hi


# -- the rule negative tests (acceptance: each hazard raises its rule) ---------


class TestRules:
    def test_int_overflow_flagged(self):
        e = Call("$mul", [InputRef(0, T.BIGINT), InputRef(1, T.BIGINT)],
                 T.BIGINT)
        _, issues = analyze_expr(e)
        assert [i.rule for i in issues] == ["int-overflow"]

    def test_int32_add_overflow_flagged(self):
        e = Call("$add", [InputRef(0, T.INTEGER), InputRef(1, T.INTEGER)],
                 T.INTEGER)
        _, issues = analyze_expr(e)
        assert [i.rule for i in issues] == ["int-overflow"]

    def test_decimal_overflow_flagged(self):
        d = T.DecimalType(15, 2)
        e = Call("$mul", [InputRef(0, d), InputRef(1, d)], T.DecimalType(18, 4))
        _, issues = analyze_expr(e)
        assert any(i.rule == "decimal-overflow" for i in issues)

    def test_scale_mismatch_flagged(self):
        e = SpecialForm(
            Form.IF,
            [
                InputRef(0, T.BOOLEAN),
                InputRef(1, T.DecimalType(10, 2)),
                Literal(Decimal(0), T.DecimalType(10, 0)),
            ],
            T.DecimalType(10, 0),
        )
        _, issues = analyze_expr(e)
        assert [i.rule for i in issues] == ["scale-mismatch"]

    def test_float_contamination_flagged(self):
        e = SpecialForm(
            Form.CAST, [InputRef(0, T.DOUBLE)], T.DecimalType(12, 2)
        )
        _, issues = analyze_expr(e)
        assert [i.rule for i in issues] == ["float-contamination"]

    def test_dropped_validity_flagged(self):
        e = SpecialForm(
            Form.ARRAY, [InputRef(0, T.BIGINT)], T.ArrayType(T.BIGINT)
        )
        _, issues = analyze_expr(e)
        assert [i.rule for i in issues] == ["dropped-validity"]

    def test_safe_expression_raises_nothing(self):
        d = T.DecimalType(12, 2)
        e = Call(
            "$mul",
            [
                InputRef(0, d),
                Call("$sub", [Literal(Decimal(1), d), InputRef(1, d)],
                     T.DecimalType(13, 2)),
            ],
            T.DecimalType(25, 4),
        )
        fact, issues = analyze_expr(e)
        assert issues == []
        assert fact.interval.bounded

    def test_stats_env_narrows_to_proven(self):
        """A by-type hazard becomes PROVEN-SAFE under stats bounds."""
        e = Call("$mul", [InputRef(0, T.BIGINT), InputRef(1, T.BIGINT)],
                 T.BIGINT)
        env = Env(channels={
            0: Fact(T.BIGINT, R.Interval(0, 100), True),
            1: Fact(T.BIGINT, R.Interval(0, 1000), True),
        })
        _, issues = analyze_expr(e, env)
        assert issues == []

    def test_untracked_operand_never_false_positives(self):
        """Unknown-function results keep honest type-wide intervals but do
        not RAISE overflow (no evidence of a hazard)."""
        inner = Call("some_udf", [InputRef(0, T.BIGINT)], T.BIGINT)
        e = Call("$mul", [inner, Literal(10**6, T.BIGINT)], T.BIGINT)
        _, issues = analyze_expr(e)
        assert issues == []

    def test_case_without_else_is_nullable(self):
        """CASE with pairs only carries the compiler's implicit NULL
        default: the fact must be nullable even over non-null inputs, so
        ARRAY[CASE WHEN c THEN 1 END] still raises dropped-validity."""
        case = SpecialForm(
            Form.CASE,
            [Literal(True, T.BOOLEAN), Literal(1, T.BIGINT)],
            T.BIGINT,
        )
        fact, issues = analyze_expr(case)
        assert fact.nullable and issues == []
        arr = SpecialForm(Form.ARRAY, [case], T.ArrayType(T.BIGINT))
        _, issues = analyze_expr(arr)
        assert [i.rule for i in issues] == ["dropped-validity"]

    def test_null_literal_branch_not_scale_mismatched(self):
        e = SpecialForm(
            Form.IF,
            [
                InputRef(0, T.BOOLEAN),
                InputRef(1, T.DecimalType(10, 2)),
                Literal(None, T.DecimalType(10, 2)),
            ],
            T.DecimalType(10, 2),
        )
        _, issues = analyze_expr(e)
        assert issues == []


# -- certificates and the licensing pass ---------------------------------------


class TestLicensing:
    def test_sum_certificate_q1_shape(self):
        d = T.DecimalType(12, 2)
        env = Env(channels={
            0: Fact(d, R.Interval(90_000, 10_500_000), True),
            1: Fact(d, R.Interval(0, 10), True),
        })
        prod = Call(
            "$mul",
            [
                InputRef(0, d),
                Call("$sub", [Literal(Decimal(1), d), InputRef(1, d)],
                     T.DecimalType(13, 2)),
            ],
            T.DecimalType(25, 4),
        )
        cert = sum_certificate(prod, env, rows_bound=6_000_000)
        assert cert is not None
        assert cert.licensed_i64_sum_bound() is not None
        assert cert.to_json()["licenses_i64_sum"] is True

    def test_no_rows_bound_no_license(self):
        d = T.DecimalType(12, 2)
        cert = sum_certificate(InputRef(0, d), Env(), rows_bound=None)
        assert cert is not None and cert.licensed_i64_sum_bound() is None

    def test_untracked_refuses(self):
        cert = sum_certificate(
            Call("some_udf", [], T.DecimalType(12, 2)), Env(), 100
        )
        assert cert is None

    def test_q1_plan_is_licensed(self):
        from trino_tpu.connectors.tpch.queries import QUERIES
        from trino_tpu.planner import plan as P
        from trino_tpu.runtime.runner import LocalQueryRunner

        r = LocalQueryRunner(catalog="tpch", schema="tiny")
        plan = r.create_plan(QUERIES[1])

        def walk(n, seen):
            if id(n) in seen:
                return
            seen.add(id(n))
            yield n
            for c in n.children:
                yield from walk(c, seen)

        sums = [
            agg
            for node in walk(plan, set())
            if isinstance(node, P.AggregationNode)
            for _, agg in node.aggregations
            if agg.function in ("sum", "avg") and agg.args
            and isinstance(agg.args[0].type, T.DecimalType)
        ]
        assert sums, "Q1 must contain decimal sums"
        assert all(a.sum_bound is not None for a in sums), [
            (a.function, a.sum_bound) for a in sums
        ]
        # the license is a REAL i64 proof
        assert all(a.sum_bound < (1 << 63) for a in sums)

    def test_row_upper_bound_sound_shapes(self):
        from trino_tpu.connectors.tpch.queries import QUERIES
        from trino_tpu.runtime.runner import LocalQueryRunner

        r = LocalQueryRunner(catalog="tpch", schema="tiny")
        plan = r.create_plan(QUERIES[1])
        b = row_upper_bound(plan, r.catalogs)
        # Q1 is scan->filter->project->agg: bounded by the lineitem count
        assert b is not None and b > 0

    def test_memory_catalog_never_licenses(self):
        """No admissible stats source -> no certificate -> unchanged
        kernels (the conservative default for user tables)."""
        from trino_tpu.planner import plan as P
        from trino_tpu.runtime.runner import LocalQueryRunner

        r = LocalQueryRunner(catalog="memory", schema="default")
        r.execute("create table lic (k bigint, v decimal(12,2))")
        r.execute("insert into lic values (1, decimal '1.00')")
        plan = r.create_plan("select k, sum(v) from lic group by k")

        def walk(n, seen):
            if id(n) in seen:
                return
            seen.add(id(n))
            yield n
            for c in n.children:
                yield from walk(c, seen)

        for node in walk(plan, set()):
            if isinstance(node, P.AggregationNode):
                for _, agg in node.aggregations:
                    assert getattr(agg, "sum_bound", None) is None

    def test_licensed_q1_results_match_unlicensed(self):
        """The license changes the kernel, never the answer: Q1 grouped
        sums with certificates equal a forced-certificate-free run."""
        from trino_tpu.runtime.runner import LocalQueryRunner

        sql = (
            "select l_returnflag, sum(l_extendedprice * (1 - l_discount)) "
            "from lineitem group by l_returnflag order by l_returnflag"
        )
        r = LocalQueryRunner(catalog="tpch", schema="tiny")
        licensed = r.execute(sql).rows
        import trino_tpu.verify.numeric as VN

        orig = VN.license_decimal_sums
        VN.license_decimal_sums = lambda plan, catalogs=None: 0
        try:
            r2 = LocalQueryRunner(catalog="tpch", schema="tiny")
            unlicensed = r2.execute(sql).rows
        finally:
            VN.license_decimal_sums = orig
        assert licensed == unlicensed


# -- the sweep gate -------------------------------------------------------------


def test_sweep_smoke_q1_q6():
    """Fast in-tier-1 slice of the CI sweep: Q1 + Q6 expressions all
    PROVEN-SAFE (no baseline needed for the headline queries)."""
    from trino_tpu.connectors.tpch.queries import QUERIES
    from trino_tpu.runtime.runner import LocalQueryRunner
    from trino_tpu.verify.numeric import SweepResult, sweep_plan

    r = LocalQueryRunner(catalog="tpch", schema="tiny")
    res = SweepResult()
    for q in (1, 6):
        sweep_plan(r.create_plan(QUERIES[q]), r.catalogs, {}, res, f"tpch:{q}")
    assert res.violations == [], res.violations
    assert res.proven == res.expressions and res.expressions > 0


@pytest.mark.slow
def test_sweep_all_benchmarks_zero_unbaselined():
    """The full acceptance gate: every TPC-H + TPC-DS plan expression is
    PROVEN-SAFE or BASELINED; any unbaselined VIOLATION fails (CI runs the
    same sweep via `python -m trino_tpu.verify.numeric`)."""
    import os

    from trino_tpu.verify.numeric import verify_benchmarks

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = verify_benchmarks(root=root)
    assert res.violations == [], [
        (w, str(i)) for w, i in res.violations[:10]
    ]
    assert res.expressions > 4000
