"""Chaos suite: injected task failures, latency spikes, flaky connections,
and dead workers across the multi-host + FTE paths.

The contract under test (the tentpole's acceptance bar): EVERY query either
returns rows equal to the local runner or fails/cancels with a CLASSIFIED
error before its deadline — never hangs, never returns wrong rows.

Marked `slow` (excluded from tier-1): these tests run real HTTP workers and
real injected latency.  The deterministic-clock halves of the machinery
(state machine, breaker transitions, backoff schedule, memory-kill victim
choice) run in tier-1 via tests/test_lifecycle.py.
"""

import threading
import time
import urllib.request

import pytest

from tests.test_e2e import assert_rows_match
from trino_tpu.parallel.remote import MultiHostQueryRunner
from trino_tpu.runtime.lifecycle import (
    QueryAbortedException,
    QueryCanceledException,
    QueryDeadlineExceeded,
)
from trino_tpu.runtime.retry import BREAKERS, FAILURE_INJECTOR, InjectedFailure
from trino_tpu.runtime.runner import LocalQueryRunner
from trino_tpu.server.worker import WorkerServer

pytestmark = [pytest.mark.slow, pytest.mark.heavy]

#: generous wall deadline: chaos queries must finish (or abort) well inside
#: it — a hang is the one outcome this suite exists to forbid
DEADLINE_S = 60.0


@pytest.fixture(scope="module", autouse=True)
def lockgraph():
    """Instrumented-lock mode (verify.lockgraph): every lock created
    during the chaos module — servers, runners, registries, background
    waiters — reports its acquisition order, and the module fails if the
    recorded graph has a cycle.  An order inversion is a deadlock waiting
    for the right interleaving, so this gate fires even on runs where the
    chaos happened not to hang."""
    from trino_tpu.verify import lockgraph as lg

    with lg.capture() as graph:
        yield graph
    graph.assert_acyclic()


@pytest.fixture(scope="module", autouse=True)
def no_spool_leaks():
    """Chaos kills must never leak spool directories: every query-owned
    spool (fault-tolerant recovery included) is removed when its query
    ends, so /tmp holds zero orphan .npz spools after the module."""
    import glob
    import os
    import tempfile

    pat = os.path.join(tempfile.gettempdir(), "trino_tpu_spool_*")
    before = set(glob.glob(pat))
    yield
    leaked = set(glob.glob(pat)) - before
    assert not leaked, f"spool directories leaked: {sorted(leaked)}"


@pytest.fixture(scope="module")
def workers(lockgraph):
    ws = [WorkerServer(port=0).start() for _ in range(2)]
    yield ws
    for w in ws:
        w.shutdown()


@pytest.fixture(scope="module")
def local():
    return LocalQueryRunner(catalog="tpch", schema="tiny")


@pytest.fixture()
def mh(workers):
    r = MultiHostQueryRunner(
        [w.url for w in workers], catalog="tpch", schema="tiny"
    )
    r.properties.set("query_max_run_time", DEADLINE_S)
    return r


@pytest.fixture(autouse=True)
def clean_chaos():
    FAILURE_INJECTOR.clear()
    BREAKERS.reset()
    yield
    FAILURE_INJECTOR.clear()
    BREAKERS.reset()


QUERIES = [
    # source fragment + gather
    "select count(*), sum(l_quantity) from lineitem",
    # hash-partitioned aggregation over an exchange
    "select l_returnflag, count(*), sum(l_extendedprice) "
    "from lineitem group by l_returnflag",
    # partitioned join (both sides repartition on the key hash)
    "select count(*) from lineitem, orders where l_orderkey = o_orderkey",
]

#: (injection point pattern, mode, times) — the sweep axis.  Points cover
#: task submission and the HTTP pull data plane (result pulls AND worker
#: input pulls share the `fetch:` hook).
INJECTIONS = [
    ("submit:", "flap", 1),
    ("submit:", "flap", 2),
    ("submit:", "error", 1),
    ("fetch:", "flap", 1),
    ("fetch:", "flap", 3),
    ("fetch:", "error", 1),
    ("fetch:", "latency", 1),
]


def _run_bounded(mh, local, sql):
    """The chaos contract: rows == local, or a classified error, and either
    way the query resolves well before its deadline."""
    t0 = time.monotonic()
    try:
        got = mh.execute(sql).rows
    except (QueryAbortedException, RuntimeError, OSError) as e:
        # classified abort, or a task/worker failure the engine surfaced
        # loudly — acceptable; silence and wrong rows are not
        assert str(e), "failure must carry a message"
        return time.monotonic() - t0, None
    wall = time.monotonic() - t0
    assert_rows_match(got, local.execute(sql).rows, ordered=False)
    return wall, got


@pytest.mark.parametrize("point,mode,times", INJECTIONS)
def test_chaos_sweep_multihost(mh, local, point, mode, times):
    """Sweep failure/latency/flaky-connection injections across the
    multi-host path: every query matches local or fails classified — and
    resolves inside the deadline either way."""
    for sql in QUERIES:
        FAILURE_INJECTOR.clear()
        BREAKERS.reset()
        if mode == "flap":
            FAILURE_INJECTOR.inject_connection_flap(point, times=times)
        elif mode == "latency":
            FAILURE_INJECTOR.inject_latency(point, delay_s=0.5, times=times)
        else:
            FAILURE_INJECTOR.inject(point, times=times, error=InjectedFailure)
        wall, got = _run_bounded(mh, local, sql)
        assert wall < DEADLINE_S, f"{point}/{mode} blew the deadline on {sql}"
        if mode in ("flap", "latency"):
            # transient chaos must be ABSORBED (retry w/ backoff, task
            # replacement), not surfaced: rows equal local
            assert got is not None, f"{point}/{mode}({times}) failed {sql}"


def test_chaos_latency_spike_absorbed(mh, local):
    """A one-off latency spike on the data plane stalls but does not break
    or mis-answer the query."""
    FAILURE_INJECTOR.inject_latency("fetch:", delay_s=1.0, times=1)
    sql = QUERIES[1]
    wall, got = _run_bounded(mh, local, sql)
    assert got is not None and wall < DEADLINE_S


def test_chaos_deadline_cuts_off_stalled_query(mh, local):
    """With the data plane stalled past query_max_run_time, the query fails
    CLASSIFIED (EXCEEDED_TIME_LIMIT) promptly after the stall — it neither
    hangs nor burns the full injected latency budget."""
    mh.properties.set("query_max_run_time", 0.5)
    FAILURE_INJECTOR.inject_latency("fetch:", delay_s=1.0, times=50)
    t0 = time.monotonic()
    with pytest.raises(QueryDeadlineExceeded) as ei:
        mh.execute(QUERIES[0])
    wall = time.monotonic() - t0
    mh.properties.set("query_max_run_time", DEADLINE_S)
    assert ei.value.error_code == "EXCEEDED_TIME_LIMIT"
    assert wall < 15.0, "deadline abort must not drain the whole stall budget"
    # the engine recovered: a clean follow-up query runs normally
    FAILURE_INJECTOR.clear()
    wall, got = _run_bounded(mh, local, QUERIES[0])
    assert got is not None


def test_chaos_dead_worker_breaker_opens_and_queries_survive(local):
    """A worker that dies keeps failing its probes/submits: the per-worker
    circuit breaker trips OPEN (visible in system.runtime.metrics) and
    queries keep answering correctly from the surviving workers."""
    ws = [WorkerServer(port=0).start() for _ in range(3)]
    victim = ws[2]
    try:
        mh = MultiHostQueryRunner(
            [w.url for w in ws], catalog="tpch", schema="tiny"
        )
        mh.properties.set("query_max_run_time", DEADLINE_S)
        victim.shutdown()
        for sql in QUERIES:
            # fresh probe evidence each query (the TTL cache would hide
            # the repeated failures the breaker needs to see)
            mh._worker_health.clear()
            wall, got = _run_bounded(mh, local, sql)
            assert got is not None and wall < DEADLINE_S
        states = BREAKERS.states()
        assert states.get(victim.url) == "open", states
        # the OPEN breaker is queryable as a labeled gauge (the system
        # catalog is coordinator-resident: query it through the local
        # runner — the breaker registry is process-wide)
        rows = local.execute(
            "select labels, value from system.runtime.metrics "
            "where name = 'trino_tpu_breaker_state'"
        ).rows
        assert any(victim.url in labels and value == 2.0
                   for labels, value in rows), rows
    finally:
        for w in ws:
            try:
                w.shutdown()
            except Exception:
                pass


def test_chaos_worker_task_cancel_is_real(workers):
    """DELETE /v1/task/{id} aborts a RUNNING task at its next cooperative
    check instead of letting it burn the slot to completion."""
    from trino_tpu.server.worker import _http_get

    # no deadline on the descriptor: the long-poll would wait RESULT_WAIT_S
    url = workers[0].url
    with urllib.request.urlopen(f"{url}/v1/info", timeout=5.0) as r:
        r.read()
    # a task id that was never submitted: DELETE must still answer 200
    req = urllib.request.Request(f"{url}/v1/task/never_there", method="DELETE")
    with urllib.request.urlopen(req, timeout=5.0) as r:
        assert r.status == 200


def test_chaos_coordinator_delete_cancels_running_query(workers, local):
    """DELETE /v1/query/{id} is a REAL cancel: the running statement aborts
    at its next cooperative check, shows CANCELED on the protocol, and the
    engine survives to run the next query."""
    from trino_tpu.server.coordinator import CoordinatorServer

    mh = MultiHostQueryRunner(
        [w.url for w in workers], catalog="tpch", schema="tiny"
    )
    server = CoordinatorServer(runner=mh, port=0)
    server.start()
    try:
        # stall the data plane so the query is mid-flight when DELETE lands
        FAILURE_INJECTOR.inject_latency("fetch:", delay_s=1.5, times=10)
        q = server.submit(QUERIES[0])
        time.sleep(0.3)  # let the executor enter the stalled fetch
        req = urllib.request.Request(
            f"http://{server.host}:{server.port}/v1/query/{q.id}",
            method="DELETE",
        )
        with urllib.request.urlopen(req, timeout=5.0) as r:
            assert r.status == 204
        assert q.done.wait(timeout=30.0), "canceled query must terminate"
        assert q.state == "CANCELED"
        assert q.error["errorCode"] == "USER_CANCELED"
        assert q.error["errorType"] == "USER_ERROR"
        # the engine is healthy afterwards
        FAILURE_INJECTOR.clear()
        q2 = server.submit("select count(*) from region")
        assert q2.done.wait(timeout=30.0) and q2.state == "FINISHED"
        # the query history records the CANCELED state + kill reason (the
        # system catalog is coordinator-resident — read it directly rather
        # than distributing a system scan to the workers)
        entries = [
            (e["state"], e["error_code"]) for e in mh.query_history.entries
        ]
        assert ("CANCELED", "USER_CANCELED") in entries
    finally:
        server.shutdown()


def test_chaos_coordinator_delete_while_queued(workers):
    """A DELETE racing statement submission cancels the query BEFORE it
    occupies the engine (cancel-while-queued)."""
    from trino_tpu.server.coordinator import CoordinatorServer

    mh = MultiHostQueryRunner(
        [w.url for w in workers], catalog="tpch", schema="tiny"
    )
    server = CoordinatorServer(runner=mh, port=0)
    server.start()
    try:
        FAILURE_INJECTOR.inject_latency("fetch:", delay_s=1.0, times=5)
        q1 = server.submit(QUERIES[0])  # occupies the engine lock
        q2 = server.submit(QUERIES[1])  # queued behind it
        q2.cancel()
        assert q2.done.wait(timeout=30.0) or q2.state == "QUEUED"
        assert q1.done.wait(timeout=30.0)
        assert q2.done.wait(timeout=30.0)
        assert q2.state == "CANCELED"
    finally:
        server.shutdown()


def test_chaos_worker_killed_mid_query_replans_at_w_minus_1(local):
    """The tentpole's acceptance bar: a worker dying MID-QUERY (tasks
    already placed on it) triggers mesh-shrink re-planning — the query
    re-fragments against the survivors (W-1) and still answers rows ==
    local inside the deadline, instead of retrying forever against the
    corpse."""
    ws = [WorkerServer(port=0).start() for _ in range(3)]
    victim = ws[2]
    killed = {"done": False}
    orig = FAILURE_INJECTOR.maybe_fail

    def kill_hook(point):
        # first data-plane pull: the victim dies under the running query
        if point.startswith("fetch:") and not killed["done"]:
            killed["done"] = True
            threading.Thread(target=victim.shutdown, daemon=True).start()
            time.sleep(0.2)  # let the socket actually close
        return orig(point)

    FAILURE_INJECTOR.maybe_fail = kill_hook
    try:
        mh = MultiHostQueryRunner(
            [w.url for w in ws], catalog="tpch", schema="tiny"
        )
        mh.properties.set("query_max_run_time", DEADLINE_S)
        sql = QUERIES[1]
        t0 = time.monotonic()
        got = mh.execute(sql).rows
        wall = time.monotonic() - t0
        assert wall < DEADLINE_S
        assert_rows_match(got, local.execute(sql).rows, ordered=False)
        assert killed["done"], "the kill hook never fired"
        assert mh.membership.state(victim.url) == "DEAD"
        assert len(mh.last_plan_workers) == 2, mh.last_plan_workers
        # the shrunk mesh is stable: the next query plans at W-1 directly
        FAILURE_INJECTOR.maybe_fail = orig
        got = mh.execute(sql).rows
        assert_rows_match(got, local.execute(sql).rows, ordered=False)
        assert mh.last_replans == 0 and len(mh.last_plan_workers) == 2
    finally:
        FAILURE_INJECTOR.maybe_fail = orig
        for w in ws:
            try:
                w.shutdown()
            except Exception:
                pass


def test_chaos_drain_mid_query_finishes_or_replans(local):
    """Graceful drain landing mid-query: the draining worker finishes its
    running tasks but refuses new submissions (503/REFUSED, no breaker
    vote), so the query either completes on the old mesh or re-plans
    without the drainee — rows == local either way, inside the deadline."""
    ws = [WorkerServer(port=0).start() for _ in range(3)]
    drainee = ws[1]
    drained = {"done": False}
    orig = FAILURE_INJECTOR.maybe_fail

    def drain_hook(point):
        # drain lands while the coordinator is mid-submission fan-out
        if point.startswith(f"submit:{drainee.url}") and not drained["done"]:
            drained["done"] = True
            drainee.begin_drain(exit_on_idle=False)
        return orig(point)

    FAILURE_INJECTOR.maybe_fail = drain_hook
    try:
        mh = MultiHostQueryRunner(
            [w.url for w in ws], catalog="tpch", schema="tiny"
        )
        mh.properties.set("query_max_run_time", DEADLINE_S)
        for sql in QUERIES:
            t0 = time.monotonic()
            got = mh.execute(sql).rows
            wall = time.monotonic() - t0
            assert wall < DEADLINE_S
            assert_rows_match(got, local.execute(sql).rows, ordered=False)
        assert drained["done"], "the drain hook never fired"
        # the drain was by choice, not failure: no breaker opened for it
        assert BREAKERS.states().get(drainee.url, "closed") != "open"
        assert drainee.url not in mh.last_plan_workers
    finally:
        FAILURE_INJECTOR.maybe_fail = orig
        for w in ws:
            try:
                w.shutdown()
            except Exception:
                pass


def test_chaos_grow_mid_query_joins_next_mesh_only(local):
    """A worker registering while a query runs never mutates the running
    mesh: the in-flight query completes on the mesh it was planned for,
    and the NEW worker serves from the next query on."""
    ws = [WorkerServer(port=0).start() for _ in range(2)]
    w3 = WorkerServer(port=0).start()
    try:
        mh = MultiHostQueryRunner(
            [w.url for w in ws], catalog="tpch", schema="tiny"
        )
        mh.properties.set("query_max_run_time", DEADLINE_S)
        # stall the data plane so the grow lands mid-flight
        FAILURE_INJECTOR.inject_latency("fetch:", delay_s=0.5, times=2)
        grown = threading.Timer(0.2, mh.add_worker, args=(w3.url,))
        grown.start()
        sql = QUERIES[0]
        got = mh.execute(sql).rows
        grown.join()
        assert_rows_match(got, local.execute(sql).rows, ordered=False)
        assert w3.url not in mh.last_plan_workers, (
            "a grow must never join a running query's mesh"
        )
        # ... but the next query's mesh includes it
        FAILURE_INJECTOR.clear()
        got = mh.execute(sql).rows
        assert_rows_match(got, local.execute(sql).rows, ordered=False)
        assert w3.url in mh.last_plan_workers
        assert len(mh.last_plan_workers) == 3
    finally:
        for w in ws + [w3]:
            try:
                w.shutdown()
            except Exception:
                pass


def test_chaos_membership_sweep_kill_each_worker(local):
    """Kill sweep: whichever worker dies mid-query, the answer is rows ==
    local or a classified failure — never a hang, never wrong rows."""
    for victim_idx in range(3):
        ws = [WorkerServer(port=0).start() for _ in range(3)]
        orig = FAILURE_INJECTOR.maybe_fail
        fired = {"done": False}

        def kill_hook(point, _v=ws[victim_idx]):
            if point.startswith("fetch:") and not fired["done"]:
                fired["done"] = True
                threading.Thread(target=_v.shutdown, daemon=True).start()
                time.sleep(0.2)
            return orig(point)

        FAILURE_INJECTOR.maybe_fail = kill_hook
        try:
            BREAKERS.reset()
            mh = MultiHostQueryRunner(
                [w.url for w in ws], catalog="tpch", schema="tiny"
            )
            mh.properties.set("query_max_run_time", DEADLINE_S)
            wall, got = _run_bounded(mh, local, QUERIES[2])
            assert wall < DEADLINE_S, f"victim {victim_idx} blew the deadline"
            assert got is not None, (
                f"victim {victim_idx}: a single death must be absorbed by "
                "mesh-shrink re-planning"
            )
        finally:
            FAILURE_INJECTOR.maybe_fail = orig
            for w in ws:
                try:
                    w.shutdown()
                except Exception:
                    pass


def test_chaos_fte_stage_failures_and_latency(local):
    """The in-mesh FTE path (retry_policy=TASK, spooled stages) under the
    new injection modes: stage failures + latency spikes retry/absorb and
    the answer still equals local."""
    from trino_tpu.parallel import DistributedQueryRunner

    r = DistributedQueryRunner(n_workers=8)
    r.properties.set("retry_policy", "TASK")
    r.properties.set("query_max_run_time", DEADLINE_S)
    sql = (
        "select l_returnflag, count(*) c, sum(l_quantity) q "
        "from lineitem group by l_returnflag order by l_returnflag"
    )
    FAILURE_INJECTOR.inject("stage:", times=2, error=InjectedFailure)
    FAILURE_INJECTOR.inject_latency("stage:", delay_s=0.3, times=2)
    t0 = time.monotonic()
    got = r.execute(sql).rows
    wall = time.monotonic() - t0
    assert got == local.execute(sql).rows
    assert wall < DEADLINE_S


def test_chaos_cancel_inmesh_mid_query():
    """Cooperative cancellation on the in-mesh SPMD path: a cancel armed
    between fragment launches aborts the query with CANCELED classification
    instead of finishing it."""
    from trino_tpu.parallel import DistributedQueryRunner

    r = DistributedQueryRunner(n_workers=8)
    cancel_after = {"n": 2}
    orig = FAILURE_INJECTOR.maybe_fail

    def cancel_hook(point):
        if point.startswith("stage:"):
            cancel_after["n"] -= 1
            if cancel_after["n"] == 0:
                ctx = __import__(
                    "trino_tpu.runtime.lifecycle", fromlist=["current_query"]
                ).current_query()
                if ctx is not None:
                    ctx.cancel("chaos cancel")
        return orig(point)

    FAILURE_INJECTOR.maybe_fail = cancel_hook
    try:
        with pytest.raises(QueryCanceledException):
            r.execute(
                "select count(*) from lineitem, orders "
                "where l_orderkey = o_orderkey"
            )
    finally:
        FAILURE_INJECTOR.maybe_fail = orig
    # the engine survives: the next statement runs clean
    assert r.execute("select count(*) from region").rows == [(5,)]


def test_chaos_pool_shrink_mid_query_revokes_join_into_waves(local):
    """Memory-pressure chaos (a): the shared pool limit SHRINKS while a
    join is mid-probe — the escalation's revoke tier asks the running
    build to spill, the probe remainder finishes in partition waves, and
    rows still equal the unconstrained local oracle (exceed -> revoke ->
    wave, killer never fires)."""
    from trino_tpu.ops.join import HashJoinOperator
    from trino_tpu.runtime import spill as S
    from trino_tpu.runtime.lifecycle import set_memory_pool_limit
    from trino_tpu.telemetry.metrics import (
        memory_kills_counter,
        memory_revocations_counter,
    )

    sql = (
        "select o_orderpriority, count(*), sum(l_quantity) from orders "
        "join lineitem on o_orderkey = l_orderkey group by o_orderpriority"
    )
    base = sorted(local.execute(sql).rows)
    rev0 = memory_revocations_counter().value()
    kills0 = memory_kills_counter().value()
    shrunk = threading.Event()
    shrinkers: list = []
    orig = HashJoinOperator._join_batch

    def shrinking(self, pb):
        out = orig(self, pb)
        if not shrunk.is_set():
            shrunk.set()
            # an operator watchdog shrinking the pool under live queries
            # to well below the join build's reservation (the query's
            # residual state still fits, so it can finish degraded)
            t = threading.Thread(
                target=set_memory_pool_limit, args=(400_000,),
                name="chaos-shrink", daemon=True,
            )
            shrinkers.append(t)
            t.start()
        return out

    HashJoinOperator._join_batch = shrinking
    try:
        r = LocalQueryRunner(catalog="tpch", schema="tiny", target_splits=4)
        t0 = time.monotonic()
        rows = sorted(r.execute(sql).rows)
        wall = time.monotonic() - t0
    finally:
        HashJoinOperator._join_batch = orig
        for t in shrinkers:
            t.join()  # a late shrink must not land AFTER the reset below
        set_memory_pool_limit(0)
    assert shrunk.is_set()
    assert wall < DEADLINE_S
    assert rows == base
    assert memory_revocations_counter().value() > rev0
    assert memory_kills_counter().value() == kills0  # killer never fired
    assert not S.REVOCABLES.live()


def test_chaos_pool_pressure_and_worker_kill_compose(local):
    """Memory-pressure chaos (b): a constrained budget AND a mid-query
    worker kill compose — the W-1 re-plan re-executes under the SAME
    budget (waves and all) and still answers rows == local, or fails
    classified inside its deadline.  Degradation tiers must not interfere
    with elastic membership."""
    ws = [WorkerServer(port=0).start() for _ in range(3)]
    victim = ws[2]
    killed = {"done": False}
    orig = FAILURE_INJECTOR.maybe_fail

    def kill_hook(point):
        if point.startswith("fetch:") and not killed["done"]:
            killed["done"] = True
            threading.Thread(target=victim.shutdown, daemon=True).start()
            time.sleep(0.2)
        return orig(point)

    FAILURE_INJECTOR.maybe_fail = kill_hook
    try:
        mh = MultiHostQueryRunner(
            [w.url for w in ws], catalog="tpch", schema="tiny"
        )
        mh.properties.set("query_max_run_time", DEADLINE_S)
        mh.properties.set("query_max_memory", 250_000)
        sql = QUERIES[2]
        t0 = time.monotonic()
        try:
            got = mh.execute(sql).rows
        except (QueryAbortedException, RuntimeError, OSError) as e:
            assert str(e), "failure must carry a message"
            got = None
        wall = time.monotonic() - t0
        assert wall < DEADLINE_S
        assert killed["done"], "the kill hook never fired"
        if got is not None:
            assert_rows_match(got, local.execute(sql).rows, ordered=False)
            assert len(mh.last_plan_workers) == 2
        # the shrunk mesh keeps answering under the same budget
        FAILURE_INJECTOR.maybe_fail = orig
        got = mh.execute(sql).rows
        assert_rows_match(got, local.execute(sql).rows, ordered=False)
    finally:
        FAILURE_INJECTOR.maybe_fail = orig
        for w in ws:
            try:
                w.shutdown()
            except Exception:
                pass


def test_chaos_concurrent_serving_kill_and_pool_shrink(local):
    """PR 13 acceptance composition: K=8 concurrent clients admitted
    through weighted-fair resource groups x a worker kill at W-1 x a
    mid-run shared-pool shrink.  Every statement either answers the local
    oracle's rows or fails CLASSIFIED (canceled | queued-time | deadline |
    memory | shed | loud worker failure) inside its deadline — zero
    hangs, and ZERO cross-group memory kills (each group's escalation
    log only ever names its own group)."""
    from trino_tpu.runtime.dispatcher import QueryDispatcher, QueryShedError
    from trino_tpu.runtime.lifecycle import set_memory_pool_limit
    from trino_tpu.runtime.resource_groups import (
        ResourceGroupConfig,
        ResourceGroupManager,
    )

    ws = [WorkerServer(port=0).start() for _ in range(3)]
    mh = MultiHostQueryRunner(
        [w.url for w in ws], catalog="tpch", schema="tiny"
    )
    mh.properties.set("query_max_run_time", DEADLINE_S)
    mh.properties.set("query_max_queued_time", DEADLINE_S)
    mgr = ResourceGroupManager(
        ResourceGroupConfig("global", hard_concurrency=2, max_queued=16)
    )
    mgr.add(
        ResourceGroupConfig(
            "a", hard_concurrency=2, max_queued=16, weight=2,
            memory_limit_bytes=64 << 20,
        )
    )
    mgr.add(
        ResourceGroupConfig(
            "b", hard_concurrency=2, max_queued=16, weight=1,
            memory_limit_bytes=64 << 20,
        )
    )
    mgr.add_user_rule("ua", "a")
    mgr.add_user_rule("ub", "b")
    dispatcher = QueryDispatcher(mh, mgr)  # multi-host: one lane
    oracles = {sql: local.execute(sql).rows for sql in QUERIES}
    outcomes = []
    olock = threading.Lock()

    def serve_client(i):
        user = "ua" if i % 2 == 0 else "ub"
        for j in range(2):
            sql = QUERIES[(i + j) % len(QUERIES)]
            t0 = time.monotonic()
            try:
                ticket = dispatcher.enqueue(user=user)
                ticket.wait()
                got = dispatcher.run_admitted(
                    ticket, lambda r: r.execute(sql)
                ).rows
            except QueryShedError:
                got = "shed"
            except (QueryAbortedException, RuntimeError, OSError) as e:
                assert str(e), "failure must carry a message"
                got = None
            wall = time.monotonic() - t0
            assert wall < DEADLINE_S, f"client {i} blew its deadline"
            with olock:
                if got not in (None, "shed"):
                    assert_rows_match(got, oracles[sql], ordered=False)
                    outcomes.append("ok")
                else:
                    outcomes.append(got or "classified")

    def chaos_monkey():
        time.sleep(0.3)
        ws[2].shutdown()  # worker kill: survivors re-plan at W-1
        time.sleep(0.2)
        set_memory_pool_limit(1 << 20)  # mid-run pool shrink
        time.sleep(0.3)
        set_memory_pool_limit(0)

    monkey = threading.Thread(target=chaos_monkey, daemon=True)
    try:
        clients = [
            threading.Thread(target=serve_client, args=(i,), daemon=True)
            for i in range(8)
        ]
        monkey.start()
        for t in clients:
            t.start()
        for t in clients:
            t.join(timeout=DEADLINE_S * 3)
            assert not t.is_alive(), "serving hung under chaos"
        monkey.join(timeout=10)
        assert outcomes.count("ok") >= 1, outcomes  # progress under chaos
        # zero cross-group memory kills: every group-escalation kill (if
        # any fired) names its OWN group — a bystander group was never
        # shot for another group's pressure
        from trino_tpu.runtime.lifecycle import memory_pool

        root = memory_pool().root
        for name in ("a", "b"):
            ctx = mgr.groups[name].memory_context(root)
            esc = ctx.on_exceeded
            assert all(g == name for g, _victim in esc.kill_log), (
                name, esc.kill_log
            )
    finally:
        set_memory_pool_limit(0)
        for w in ws:
            try:
                w.shutdown()
            except Exception:
                pass
